"""Qwen2 family (ref capability: PaddleNLP
``paddlenlp/transformers/qwen2/modeling.py``).

LLaMA architecture + biases on the (fused) q/k/v projections, GQA, rope
theta 1e6, tied embeddings on the small variants. Shares the decoder stack
with :mod:`paddle_tpu.models.llama` (`attention_bias=True` adds the fused
qkv bias, tp-sharded with the projection).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    num_flops_per_token,
)


class Qwen2Config(LlamaConfig):
    @staticmethod
    def qwen2_7b(**kw):
        return Qwen2Config(**{**dict(
            vocab_size=152064, hidden_size=3584, intermediate_size=18944,
            num_hidden_layers=28, num_attention_heads=28,
            num_key_value_heads=4, max_position_embeddings=32768,
            rope_theta=1e6, attention_bias=True), **kw})

    @staticmethod
    def qwen2_0_5b(**kw):
        return Qwen2Config(**{**dict(
            vocab_size=151936, hidden_size=896, intermediate_size=4864,
            num_hidden_layers=24, num_attention_heads=14,
            num_key_value_heads=2, max_position_embeddings=32768,
            rope_theta=1e6, attention_bias=True,
            tie_word_embeddings=True), **kw})

    @staticmethod
    def tiny(**kw):
        return Qwen2Config(**{**dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            attention_bias=True, tie_word_embeddings=True,
            dtype=jnp.float32, remat=False), **kw})


class Qwen2Model(LlamaModel):
    pass


class Qwen2ForCausalLM(LlamaForCausalLM):
    pass
