"""Conformer ASR encoder + CTC head (ref capability: PaddleSpeech
``paddlespeech/s2t/models/u2/`` conformer encoder & CTC decoder).

TPU-first notes:
- time-major work stays [B, T, D] with D on the lane axis; the conv module
  is a depthwise 1-D conv (``lax.conv_general_dilated`` with feature_group_
  count=D) between two pointwise matmuls — all MXU/VPU friendly, no
  dynamic shapes. Padding is handled by masks, not ragged tensors.
- attention uses rotary position embedding instead of the reference's
  relative-position Transformer-XL bias: same translation-equivariance
  property, one elementwise rotation instead of a gather-heavy bias table.
- CTC loss is the scan-DP from nn.functional (log-space forward algorithm).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Conv2D, Dropout, LayerNorm, Linear
from paddle_tpu.ops import attention as A

__all__ = ["ConformerConfig", "ConformerEncoder", "ConformerForCTC"]


@dataclass
class ConformerConfig:
    n_mels: int = 80
    d_model: int = 256
    num_heads: int = 4
    num_layers: int = 12
    ff_mult: int = 4
    conv_kernel: int = 15
    vocab_size: int = 5000
    dropout: float = 0.1
    dtype: object = None

    @classmethod
    def tiny(cls, **kw):
        return cls(**{**dict(n_mels=20, d_model=32, num_heads=2, num_layers=2,
                             conv_kernel=7, vocab_size=50, dropout=0.0), **kw})


class _FeedForward(Module):
    def __init__(self, d, mult, dropout, dtype):
        super().__init__()
        self.norm = LayerNorm(d, dtype=dtype)
        self.fc1 = Linear(d, d * mult, dtype=dtype)
        self.fc2 = Linear(d * mult, d, dtype=dtype)
        self.drop = Dropout(dropout)

    def __call__(self, x, rng=None):
        y = self.fc1(self.norm(x))
        y = self.drop(jax.nn.silu(y), rng=rng)
        return self.fc2(y)


class _ConvModule(Module):
    """pointwise→GLU→depthwise→norm→swish→pointwise (ref conv module)."""

    def __init__(self, d, kernel, dropout, dtype):
        super().__init__()
        self.norm = LayerNorm(d, dtype=dtype)
        self.pw1 = Linear(d, 2 * d, dtype=dtype)
        bound = (1.0 / kernel) ** 0.5
        self.dw = I.Uniform(-bound, bound)((kernel, d), dtype)  # [K, D]
        # LN instead of the reference's BatchNorm: batch stats don't mix
        # with padding masks under jit; LN is the standard TPU substitute
        self.dw_norm = LayerNorm(d, dtype=dtype)
        self.pw2 = Linear(d, d, dtype=dtype)
        self.drop = Dropout(dropout)
        self.kernel = kernel

    def __call__(self, x, mask=None, rng=None):
        # x [B, T, D]; mask [B, T] True=valid
        y = F.glu(self.pw1(self.norm(x)), axis=-1)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        # depthwise conv along T: one grouped conv, SAME padding
        lhs = jnp.swapaxes(y, 1, 2)                   # [B, D, T]
        rhs = jnp.swapaxes(self.dw, 0, 1)[:, None, :]  # [D, 1, K]
        out = jax.lax.conv_general_dilated(
            lhs.astype(jnp.float32), rhs.astype(jnp.float32),
            window_strides=(1,), padding="SAME",
            feature_group_count=y.shape[-1])
        y = jnp.swapaxes(out, 1, 2).astype(x.dtype)   # [B, T, D]
        y = jax.nn.silu(self.dw_norm(y))
        return self.drop(self.pw2(y), rng=rng)


class _SelfAttention(Module):
    def __init__(self, d, heads, dropout, dtype):
        super().__init__()
        self.norm = LayerNorm(d, dtype=dtype)
        self.qkv = Linear(d, 3 * d, dtype=dtype)
        self.out = Linear(d, d, dtype=dtype)
        self.drop = Dropout(dropout)
        self.heads = heads

    def __call__(self, x, mask=None, rng=None):
        b, t, d = x.shape
        h = self.heads
        qkv = self.qkv(self.norm(x)).reshape(b, t, 3, h, d // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        cos, sin = A.rope_cos_sin(t, d // h, dtype=jnp.float32)
        q = A.apply_rope(q, cos, sin)
        k = A.apply_rope(k, cos, sin)
        attn_mask = None
        if mask is not None:  # block attention into padded frames
            attn_mask = mask[:, None, None, :]        # [B,1,1,T] bool
        y = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        return self.drop(self.out(y.reshape(b, t, d)), rng=rng)


class ConformerBlock(Module):
    def __init__(self, cfg: ConformerConfig, dtype):
        super().__init__()
        self.ff1 = _FeedForward(cfg.d_model, cfg.ff_mult, cfg.dropout, dtype)
        self.attn = _SelfAttention(cfg.d_model, cfg.num_heads, cfg.dropout, dtype)
        self.conv = _ConvModule(cfg.d_model, cfg.conv_kernel, cfg.dropout, dtype)
        self.ff2 = _FeedForward(cfg.d_model, cfg.ff_mult, cfg.dropout, dtype)
        self.final_norm = LayerNorm(cfg.d_model, dtype=dtype)

    def __call__(self, x, mask=None, rng=None):
        # independent dropout masks per sub-module
        r = (None,) * 4 if rng is None else tuple(jax.random.split(rng, 4))
        x = x + 0.5 * self.ff1(x, rng=r[0])           # macaron half-step
        x = x + self.attn(x, mask=mask, rng=r[1])
        x = x + self.conv(x, mask=mask, rng=r[2])
        x = x + 0.5 * self.ff2(x, rng=r[3])
        return self.final_norm(x)


class _ConvSubsample(Module):
    """Two stride-2 convs: 4× time reduction (ref Conv2dSubsampling4)."""

    def __init__(self, n_mels, d_model, dtype):
        super().__init__()
        self.conv1 = Conv2D(1, d_model, 3, stride=2, padding=1, dtype=dtype)
        self.conv2 = Conv2D(d_model, d_model, 3, stride=2, padding=1, dtype=dtype)
        self.proj = Linear(d_model * ((n_mels + 3) // 4), d_model, dtype=dtype)

    def __call__(self, feats):
        # feats [B, T, n_mels] → [B, T//4, d_model]
        x = feats[:, None]                             # [B, 1, T, M]
        x = jax.nn.relu(self.conv1(x))
        x = jax.nn.relu(self.conv2(x))                 # [B, D, T/4, M/4]
        b, d, t, m = x.shape
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b, t, d * m)
        return self.proj(x)


class ConformerEncoder(Module):
    def __init__(self, cfg: ConformerConfig):
        super().__init__()
        dtype = cfg.dtype or get_default_dtype()
        self.cfg = cfg
        self.subsample = _ConvSubsample(cfg.n_mels, cfg.d_model, dtype)
        self.blocks = [ConformerBlock(cfg, dtype) for _ in range(cfg.num_layers)]

    def __call__(self, feats, feat_lengths=None, rng=None):
        """feats [B, T, n_mels] → (hidden [B, T//4, D], out_lengths [B])."""
        x = self.subsample(feats)
        t_out = x.shape[1]
        if feat_lengths is not None:
            out_len = jnp.minimum((feat_lengths + 3) // 4, t_out)
            mask = jnp.arange(t_out)[None, :] < out_len[:, None]
        else:
            out_len = jnp.full((x.shape[0],), t_out, jnp.int32)
            mask = None
        for i, blk in enumerate(self.blocks):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = blk(x, mask=mask, rng=sub)
        return x, out_len


class ConformerForCTC(Module):
    """Encoder + CTC projection; ``loss`` is the training objective and
    ``greedy_decode`` collapses repeats/blanks (blank id 0)."""

    def __init__(self, cfg: ConformerConfig):
        super().__init__()
        dtype = cfg.dtype or get_default_dtype()
        self.cfg = cfg
        self.encoder = ConformerEncoder(cfg)
        self.ctc_head = Linear(cfg.d_model, cfg.vocab_size, dtype=dtype)

    def __call__(self, feats, feat_lengths=None, rng=None):
        hidden, out_len = self.encoder(feats, feat_lengths, rng=rng)
        return self.ctc_head(hidden), out_len

    def loss(self, feats, feat_lengths, labels, label_lengths, rng=None):
        logits, out_len = self(feats, feat_lengths, rng=rng)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # F.ctc_loss is time-major ([T, B, C], reference convention)
        return F.ctc_loss(jnp.swapaxes(logp, 0, 1), labels, out_len,
                          label_lengths, blank=0, reduction="mean")

    def greedy_decode(self, feats, feat_lengths=None):
        logits, out_len = self(feats, feat_lengths)
        ids = jnp.argmax(logits, axis=-1)              # [B, T]
        prev = jnp.pad(ids[:, :-1], ((0, 0), (1, 0)), constant_values=-1)
        t_idx = jnp.arange(ids.shape[1])[None, :]
        keep = (ids != 0) & (ids != prev) & (t_idx < out_len[:, None])
        return jnp.where(keep, ids, -1), out_len       # -1 marks dropped slots
