"""Auto registry (ref: PaddleNLP ``paddlenlp.transformers.AutoModel*`` /
HF ``AutoModelForCausalLM``): one entry point that maps an HF config's
``architectures``/``model_type`` onto the right (config, model, loader)
triple of this zoo.

Usage with a LOCAL checkpoint directory (zero-egress environment — no
hub downloads; ref AutoModel.from_pretrained):

    model = auto_from_pretrained("/path/to/ckpt")        # reads
    # config.json + *.safetensors via models.convert.load_safetensors

or from in-memory pieces:

    model = auto_from_config(cfg_dict)                   # random init
    model = AUTO_REGISTRY["llama"].load(model, state_dict)
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields


@dataclass(frozen=True)
class _Entry:
    config_cls: object
    model_cls: object
    load: object                        # load_*_state_dict(model, sd)
    # HF config key -> our config field (identity where omitted)
    remap: tuple = ()


def _registry():
    from paddle_tpu.models import albert, big_bird, deberta, distilbert
    from paddle_tpu.models import layoutlm
    from paddle_tpu.models import bart, bert, bloom, electra, ernie, falcon
    from paddle_tpu.models import ernie_m, fnet, megatron_bert, mpnet
    from paddle_tpu.models import nezha, roformer
    from paddle_tpu.models import gemma, glm, gpt, gpt_neox, gptj, llama
    from paddle_tpu.models import mixtral, opt, phi, qwen, qwen2_moe
    from paddle_tpu.models import roberta, t5
    from paddle_tpu.models import xlnet
    from paddle_tpu.models import convert as C

    return {
        "albert": _Entry(albert.AlbertConfig, albert.AlbertForMaskedLM,
                         C.load_albert_state_dict),
        "big_bird": _Entry(big_bird.BigBirdConfig,
                           big_bird.BigBirdForMaskedLM,
                           C.load_big_bird_state_dict),
        "deberta-v2": _Entry(deberta.DebertaV2Config,
                             deberta.DebertaV2ForMaskedLM,
                             C.load_deberta_v2_state_dict),
        "distilbert": _Entry(distilbert.DistilBertConfig,
                             distilbert.DistilBertForMaskedLM,
                             C.load_distilbert_state_dict),
        "layoutlm": _Entry(layoutlm.LayoutLMConfig,
                           layoutlm.LayoutLMForMaskedLM,
                           C.load_layoutlm_state_dict),
        "glm": _Entry(glm.GlmConfig, glm.GlmForCausalLM,
                      C.load_glm_state_dict),
        "mixtral": _Entry(mixtral.MixtralConfig, mixtral.MixtralForCausalLM,
                          C.load_mixtral_state_dict),
        "llama": _Entry(llama.LlamaConfig, llama.LlamaForCausalLM,
                        C.load_llama_state_dict),
        "mistral": _Entry(llama.LlamaConfig, llama.LlamaForCausalLM,
                          C.load_llama_state_dict),
        "qwen2": _Entry(qwen.Qwen2Config, qwen.Qwen2ForCausalLM,
                        C.load_llama_state_dict),
        "qwen2_moe": _Entry(qwen2_moe.Qwen2MoeConfig,
                            qwen2_moe.Qwen2MoeForCausalLM,
                            C.load_qwen2_moe_state_dict),
        "gemma": _Entry(gemma.GemmaConfig, gemma.GemmaForCausalLM,
                        C.load_gemma_state_dict),
        "bloom": _Entry(bloom.BloomConfig, bloom.BloomForCausalLM,
                        C.load_bloom_state_dict),
        "falcon": _Entry(falcon.FalconConfig, falcon.FalconForCausalLM,
                         C.load_falcon_state_dict),
        "gpt_neox": _Entry(gpt_neox.GPTNeoXConfig,
                           gpt_neox.GPTNeoXForCausalLM,
                           C.load_gpt_neox_state_dict),
        "gptj": _Entry(gptj.GPTJConfig, gptj.GPTJForCausalLM,
                       C.load_gptj_state_dict),
        "opt": _Entry(opt.OPTConfig, opt.OPTForCausalLM,
                      C.load_opt_state_dict),
        "phi": _Entry(phi.PhiConfig, phi.PhiForCausalLM,
                      C.load_phi_state_dict),
        "gpt2": _Entry(gpt.GPTConfig, gpt.GPTForCausalLM,
                       C.load_gpt2_state_dict,
                       remap=(("n_embd", "hidden_size"),
                              ("n_layer", "num_hidden_layers"),
                              ("n_head", "num_attention_heads"),
                              ("n_inner", "intermediate_size"),
                              ("n_positions", "max_position_embeddings"))),
        "bert": _Entry(bert.BertConfig, bert.BertForPretraining,
                       C.load_bert_state_dict),
        "ernie": _Entry(ernie.ErnieConfig, ernie.ErnieForMaskedLM,
                        C.load_ernie_state_dict),
        "roberta": _Entry(roberta.RobertaConfig, roberta.RobertaForMaskedLM,
                          C.load_roberta_state_dict),
        "electra": _Entry(electra.ElectraConfig,
                          electra.ElectraForPreTraining,
                          C.load_electra_state_dict),
        "bart": _Entry(bart.BartConfig, bart.BartForConditionalGeneration,
                       C.load_bart_state_dict),
        "mbart": _Entry(bart.MBartConfig,
                        bart.MBartForConditionalGeneration,
                        C.load_bart_state_dict),
        "pegasus": _Entry(bart.PegasusConfig,
                          bart.PegasusForConditionalGeneration,
                          C.load_bart_state_dict),
        "ernie_m": _Entry(ernie_m.ErnieMConfig, ernie_m.ErnieMModel,
                          C.load_ernie_m_state_dict),
        "roformer": _Entry(roformer.RoFormerConfig,
                           roformer.RoFormerForMaskedLM,
                           C.load_roformer_state_dict),
        "fnet": _Entry(fnet.FNetConfig, fnet.FNetForMaskedLM,
                       C.load_fnet_state_dict),
        "megatron-bert": _Entry(megatron_bert.MegatronBertConfig,
                                megatron_bert.MegatronBertForMaskedLM,
                                C.load_megatron_bert_state_dict),
        "mpnet": _Entry(mpnet.MPNetConfig, mpnet.MPNetForMaskedLM,
                        C.load_mpnet_state_dict),
        "nezha": _Entry(nezha.NezhaConfig, nezha.NezhaForMaskedLM,
                        C.load_nezha_state_dict),
        "blenderbot": _Entry(bart.BlenderbotConfig,
                             bart.BlenderbotForConditionalGeneration,
                             C.load_bart_state_dict),
        "blenderbot-small": _Entry(
            bart.BlenderbotSmallConfig,
            bart.BlenderbotSmallForConditionalGeneration,
            C.load_bart_state_dict),
        "codegen": _Entry(gptj.CodeGenConfig, gptj.CodeGenForCausalLM,
                          C.load_codegen_state_dict),
        "t5": _Entry(t5.T5Config, t5.T5ForConditionalGeneration,
                     C.load_t5_state_dict),
        "xlnet": _Entry(xlnet.XLNetConfig, xlnet.XLNetLMHeadModel,
                        C.load_xlnet_state_dict),
    }


def auto_config(model_type: str, hf_cfg: dict):
    """Build our config dataclass from an HF config dict: shared field
    names copy over; unknown HF keys are ignored (they configure parts
    the zoo model derives or does not need)."""
    entry = _registry()[model_type]
    names = {f.name for f in fields(entry.config_cls)}
    # None means "derive the default" in HF configs (e.g. gpt2 n_inner)
    kw = {k: v for k, v in hf_cfg.items() if k in names and v is not None}
    for theirs, ours in entry.remap:
        if hf_cfg.get(theirs) is not None:
            kw[ours] = hf_cfg[theirs]
    if "mlp_only_layers" in kw and isinstance(kw["mlp_only_layers"], list):
        kw["mlp_only_layers"] = tuple(kw["mlp_only_layers"])
    return entry.config_cls(**kw)


def auto_from_config(hf_cfg: dict):
    """Random-init model from an HF config dict (``model_type`` key)."""
    mt = hf_cfg["model_type"]
    return _registry()[mt].model_cls(auto_config(mt, hf_cfg))


def auto_from_pretrained(path: str, dtype=None):
    """Load a LOCAL HF checkpoint directory: config.json + safetensors
    shards (dependency-free reader from models.convert)."""
    from paddle_tpu.models.convert import load_safetensors

    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    mt = hf_cfg["model_type"]
    if mt not in _registry():
        raise ValueError(
            f"model_type {mt!r} is not in the auto registry; supported: "
            f"{sorted(_registry())}")
    model = auto_from_config(hf_cfg)
    sd = {}
    shards = [fn for fn in sorted(os.listdir(path))
              if fn.endswith(".safetensors")]
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for fn in shards:
        sd.update(load_safetensors(os.path.join(path, fn)))
    return _registry()[mt].load(model, sd, dtype=dtype)
