"""DeBERTa-v2/v3 (ref: PaddleNLP ``paddlenlp/transformers/deberta_v2``).

The disentangled-attention encoder: attention scores are the sum of
content-to-content, content-to-POSITION and POSITION-to-content terms,
each scaled by ``1/sqrt(d * scale_factor)``, where positions are
log-bucketed relative distances looked up in ONE shared relative
embedding table (projected through the same q/k projections when
``share_att_key``). Post-LN blocks; optional factorized embedding.
Encoder-only (q_len == k_len), matching the HF reference numerics
(tests/test_convert.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear


@dataclass
class DebertaV2Config:
    vocab_size: int = 128100
    hidden_size: int = 1536
    embedding_size: int = None           # != hidden -> projected
    num_hidden_layers: int = 24
    num_attention_heads: int = 24
    intermediate_size: int = 6144
    max_position_embeddings: int = 512
    type_vocab_size: int = 0
    position_biased_input: bool = False
    relative_attention: bool = True
    position_buckets: int = 256
    max_relative_positions: int = -1     # -1 -> max_position_embeddings
    pos_att_type: tuple = ("p2c", "c2p")
    share_att_key: bool = True
    norm_rel_ebd: str = "layer_norm"
    layer_norm_eps: float = 1e-7
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.embedding_size is None:
            self.embedding_size = self.hidden_size
        if self.max_relative_positions < 1:
            self.max_relative_positions = self.max_position_embeddings

    @property
    def pos_ebd_size(self):
        return (self.position_buckets if self.position_buckets > 0
                else self.max_relative_positions)

    @staticmethod
    def tiny(**kw):
        return DebertaV2Config(**{**dict(vocab_size=128, hidden_size=32,
                                         num_hidden_layers=2,
                                         num_attention_heads=2,
                                         intermediate_size=64,
                                         max_position_embeddings=64,
                                         position_buckets=4,
                                         layer_norm_eps=1e-7), **kw})


def make_log_bucket_position(rel, bucket_size: int, max_position: int):
    """HF's log-bucketed relative distance: exact within +-bucket/2,
    logarithmic out to max_position beyond."""
    sign = jnp.sign(rel).astype(jnp.float32)
    mid = bucket_size // 2
    abs_pos = jnp.where((rel < mid) & (rel > -mid), mid - 1,
                        jnp.abs(rel)).astype(jnp.float32)
    log_pos = jnp.ceil(jnp.log(abs_pos / mid)
                       / math.log((max_position - 1) / mid)
                       * (mid - 1)) + mid
    return jnp.where(abs_pos <= mid, rel,
                     (log_pos * sign).astype(jnp.int32))


class DisentangledSelfAttention(Module):
    def __init__(self, cfg: DebertaV2Config):
        super().__init__()
        h = cfg.hidden_size
        self.query_proj = Linear(h, h, dtype=cfg.dtype)
        self.key_proj = Linear(h, h, dtype=cfg.dtype)
        self.value_proj = Linear(h, h, dtype=cfg.dtype)
        self.dense = Linear(h, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.cfg_ref = cfg

    def __call__(self, x, rel_emb, attn_mask=None):
        cfg = self.cfg_ref
        b, s, hd = x.shape
        nh = cfg.num_attention_heads
        d = hd // nh

        def heads(t):
            return t.reshape(b, s, nh, d).transpose(0, 2, 1, 3)

        q = heads(self.query_proj(x))
        k = heads(self.key_proj(x))
        v = heads(self.value_proj(x))
        sf = 1 + len(tuple(cfg.pos_att_type))
        scale = math.sqrt(d * sf)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / scale

        if cfg.relative_attention:
            span = cfg.pos_ebd_size
            rel = (jnp.arange(s)[:, None]
                   - jnp.arange(s)[None, :]).astype(jnp.int32)
            if cfg.position_buckets > 0:
                rel = make_log_bucket_position(rel, cfg.position_buckets,
                                               cfg.max_relative_positions)
            table = rel_emb[: span * 2]                  # [2A, H]
            # share_att_key: positions go through the SAME q/k projections
            pos_k = self.key_proj(table).reshape(2 * span, nh, d)
            pos_q = self.query_proj(table).reshape(2 * span, nh, d)
            if "c2p" in cfg.pos_att_type:
                qp = jnp.einsum("bhqd,phd->bhqp", q, pos_k)  # [B,H,S,2A]
                idx = jnp.clip(rel + span, 0, 2 * span - 1)
                c2p = jnp.take_along_axis(
                    qp, jnp.broadcast_to(idx[None, None], (b, nh, s, s)),
                    axis=-1)
                scores = scores + c2p / scale
            if "p2c" in cfg.pos_att_type:
                kp = jnp.einsum("bhkd,phd->bhkp", k, pos_q)  # [B,H,S,2A]
                idx = jnp.clip(-rel + span, 0, 2 * span - 1)
                p2c = jnp.take_along_axis(
                    kp, jnp.broadcast_to(idx[None, None], (b, nh, s, s)),
                    axis=-1)
                scores = scores + p2c.transpose(0, 1, 3, 2) / scale

        if attn_mask is not None:
            scores = scores + attn_mask
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, hd)
        return self.out_norm(x + self.dense(out))


class DebertaV2Layer(Module):
    def __init__(self, cfg: DebertaV2Config):
        super().__init__()
        self.attention = DisentangledSelfAttention(cfg)
        self.intermediate = Linear(cfg.hidden_size, cfg.intermediate_size,
                                   dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, cfg.hidden_size,
                             dtype=cfg.dtype)
        self.out_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)

    def __call__(self, x, rel_emb, attn_mask=None):
        x = self.attention(x, rel_emb, attn_mask)
        m = self.output(F.gelu(self.intermediate(x)))
        return self.out_norm(x + m)


class DebertaV2Model(Module):
    def __init__(self, cfg: DebertaV2Config):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        e = cfg.embedding_size
        self.word_embeddings = Embedding(cfg.vocab_size, e,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = (
            Embedding(cfg.max_position_embeddings, e, weight_init=init,
                      dtype=cfg.dtype) if cfg.position_biased_input
            else None)
        self.token_type_embeddings = (
            Embedding(cfg.type_vocab_size, e, weight_init=init,
                      dtype=cfg.dtype) if cfg.type_vocab_size > 0 else None)
        self.embed_proj = (init((e, cfg.hidden_size), cfg.dtype)
                           if e != cfg.hidden_size else None)
        self.emb_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.layers = [DebertaV2Layer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.rel_embeddings = (init((cfg.pos_ebd_size * 2, cfg.hidden_size),
                                    cfg.dtype)
                               if cfg.relative_attention else None)
        self.rel_norm = (LayerNorm(cfg.hidden_size,
                                   epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
                         if cfg.relative_attention
                         and "layer_norm" in cfg.norm_rel_ebd else None)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        x = self.word_embeddings(input_ids)
        if self.position_embeddings is not None:
            x = x + self.position_embeddings(jnp.arange(s)[None, :])
        if self.token_type_embeddings is not None:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + self.token_type_embeddings(token_type_ids)
        if self.embed_proj is not None:
            x = x @ self.embed_proj
        x = self.emb_norm(x)
        mask = None
        if attention_mask is not None:
            mask = (1.0 - attention_mask[:, None, None, :]
                    .astype(jnp.float32)) * -1e9
        rel = self.rel_embeddings
        if rel is not None and self.rel_norm is not None:
            rel = self.rel_norm(rel)
        for lyr in self.layers:
            x = lyr(x, rel, mask)
        return x


class DebertaV2ForMaskedLM(Module):
    def __init__(self, cfg: DebertaV2Config):
        super().__init__()
        self.cfg = cfg
        self.deberta = DebertaV2Model(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq = self.deberta(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        emb = self.deberta.word_embeddings.weight
        logits = h @ emb.T
        if self.cfg.embedding_size != self.cfg.hidden_size:
            raise NotImplementedError(
                "factorized-embedding MLM head (hidden != embedding_size) "
                "needs the embedding-space transform; classification "
                "fine-tuning does not use the MLM head")
        return logits + self.mlm_bias

    def loss(self, input_ids, mlm_labels, token_type_ids=None,
             attention_mask=None):
        logits = self(input_ids, token_type_ids, attention_mask)
        ce = F.cross_entropy(logits.astype(jnp.float32),
                             jnp.maximum(mlm_labels, 0), reduction="none")
        mask = (mlm_labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
