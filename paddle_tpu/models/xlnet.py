"""XLNet (ref: PaddleNLP ``paddlenlp/transformers/xlnet/modeling.py``).

The Transformer-XL-relative-attention member of the zoo: attention
scores are content-content plus a position term computed against a
sinusoidal RELATIVE position encoding (with the rel-shift trick aligning
each query row's distances), each with its own learned bias vector
(r_w_bias / r_r_bias), plus an optional segment term (r_s_bias +
seg_embed). This implements the standard single-(content-)stream forward
— what ``XLNetLMHeadModel`` computes without ``perm_mask``/``mems`` —
which is bidirectional (attn_type="bi"); the two-stream permutation-LM
machinery is a pretraining-only device.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear


@dataclass
class XLNetConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layer: int = 12
    n_head: int = 12
    d_inner: int = 3072
    d_head: int = None                   # default d_model // n_head
    ff_activation: str = "gelu"
    layer_norm_eps: float = 1e-12
    clamp_len: int = -1
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.d_head is None:
            self.d_head = self.d_model // self.n_head
        if self.ff_activation not in ("gelu", "relu"):
            raise ValueError(f"ff_activation {self.ff_activation!r} not "
                             "supported (gelu | relu)")

    @staticmethod
    def tiny(**kw):
        return XLNetConfig(**{**dict(vocab_size=128, d_model=32, n_layer=2,
                                     n_head=4, d_inner=64), **kw})


def _rel_shift(x, klen):
    """Transformer-XL's relative-shift: [B, N, Q, Q+K] position scores
    realigned so column j of row i holds distance i - j + ..."""
    b, n, i, j = x.shape
    x = x.reshape(b, n, j, i)[:, :, 1:, :].reshape(b, n, i, j - 1)
    return x[:, :, :, :klen]


class XLNetRelativeAttention(Module):
    def __init__(self, cfg: XLNetConfig):
        super().__init__()
        d, n, dh = cfg.d_model, cfg.n_head, cfg.d_head
        init = I.Normal(0.0, cfg.initializer_range)
        self.q = init((d, n, dh), cfg.dtype)
        self.k = init((d, n, dh), cfg.dtype)
        self.v = init((d, n, dh), cfg.dtype)
        self.o = init((d, n, dh), cfg.dtype)
        self.r = init((d, n, dh), cfg.dtype)
        self.r_w_bias = jnp.zeros((n, dh), cfg.dtype)
        self.r_r_bias = jnp.zeros((n, dh), cfg.dtype)
        self.r_s_bias = jnp.zeros((n, dh), cfg.dtype)
        self.seg_embed = init((2, n, dh), cfg.dtype)
        self.layer_norm = LayerNorm(d, epsilon=cfg.layer_norm_eps,
                                    dtype=cfg.dtype)
        self.scale = 1.0 / (cfg.d_head ** 0.5)

    def __call__(self, h, pos_emb, seg_mat=None, key_mask=None):
        # h: [B, S, D]; pos_emb: [P, D] (P = 2S for attn_type="bi");
        # key_mask: [B, S] bool, True = real token (pad keys masked out)
        s = h.shape[1]
        qh = jnp.einsum("bsd,dnh->bsnh", h, self.q)
        kh = jnp.einsum("bsd,dnh->bsnh", h, self.k)
        vh = jnp.einsum("bsd,dnh->bsnh", h, self.v)
        kr = jnp.einsum("pd,dnh->pnh", pos_emb, self.r)

        ac = jnp.einsum("binh,bjnh->bnij", qh + self.r_w_bias, kh)
        bd = jnp.einsum("binh,pnh->bnip", qh + self.r_r_bias, kr)
        bd = _rel_shift(bd, klen=s)
        score = ac + bd
        if seg_mat is not None:
            ef = jnp.einsum("binh,snh->bins", qh + self.r_s_bias,
                            self.seg_embed)
            score = score + jnp.einsum("bijs,bins->bnij", seg_mat, ef)
        score = score * self.scale
        if key_mask is not None:         # HF: attn_score - 1e30 * mask
            score = score - 1e30 * (~key_mask[:, None, None, :]).astype(
                jnp.float32)
        probs = jax.nn.softmax(score.astype(jnp.float32),
                               axis=-1).astype(h.dtype)
        vec = jnp.einsum("bnij,bjnh->binh", probs, vh)
        out = jnp.einsum("binh,dnh->bid", vec, self.o)
        return self.layer_norm(h + out)


class XLNetLayer(Module):
    def __init__(self, cfg: XLNetConfig):
        super().__init__()
        self.rel_attn = XLNetRelativeAttention(cfg)
        self.layer_1 = Linear(cfg.d_model, cfg.d_inner, dtype=cfg.dtype)
        self.layer_2 = Linear(cfg.d_inner, cfg.d_model, dtype=cfg.dtype)
        self.ff_norm = LayerNorm(cfg.d_model, epsilon=cfg.layer_norm_eps,
                                 dtype=cfg.dtype)
        self.act = F.gelu if cfg.ff_activation == "gelu" else F.relu

    def __call__(self, h, pos_emb, seg_mat=None, key_mask=None):
        h = self.rel_attn(h, pos_emb, seg_mat, key_mask)
        return self.ff_norm(h + self.layer_2(self.act(self.layer_1(h))))


class XLNetModel(Module):
    def __init__(self, cfg: XLNetConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embedding = Embedding(cfg.vocab_size, cfg.d_model,
                                        weight_init=init, dtype=cfg.dtype)
        self.layers = [XLNetLayer(cfg) for _ in range(cfg.n_layer)]

    def _pos_emb(self, s):
        cfg = self.cfg
        inv = 1.0 / (10000.0 ** (jnp.arange(0, cfg.d_model, 2.0)
                                 / cfg.d_model))
        pos = jnp.arange(s, -s, -1.0)            # attn_type="bi": [S, -S)
        if cfg.clamp_len > 0:
            pos = jnp.clip(pos, -cfg.clamp_len, cfg.clamp_len)
        ang = jnp.outer(pos, inv)
        return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)],
                               axis=-1).astype(cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None,
                 attention_mask=None):
        s = input_ids.shape[1]
        pos_emb = self._pos_emb(s)
        seg_mat = None
        if token_type_ids is not None:
            # HF convention: one_hot(tt_i != tt_j) — class 0 = same segment
            diff = (token_type_ids[:, :, None]
                    != token_type_ids[:, None, :]).astype(jnp.int32)
            seg_mat = jax.nn.one_hot(diff, 2, dtype=self.cfg.dtype)
        key_mask = (attention_mask.astype(bool)
                    if attention_mask is not None else None)
        x = self.word_embedding(input_ids)
        for lyr in self.layers:
            x = lyr(x, pos_emb, seg_mat, key_mask)
        return x


class XLNetLMHeadModel(Module):
    def __init__(self, cfg: XLNetConfig):
        super().__init__()
        self.cfg = cfg
        self.transformer = XLNetModel(cfg)
        self.lm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None,
                 attention_mask=None):
        h = self.transformer(input_ids, token_type_ids, attention_mask)
        return h @ self.transformer.word_embedding.weight.T + self.lm_bias
