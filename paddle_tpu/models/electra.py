"""ELECTRA (ref: PaddleNLP ``paddlenlp/transformers/electra/modeling.py``).

BERT-style encoder with a factorized embedding: embeddings live in
``embedding_size`` dims (often < hidden) and are linearly projected up
before the first block. ``ElectraForPreTraining`` is the replaced-token
DISCRIMINATOR — a per-token binary head — which is the half of the
ELECTRA objective that makes it sample-efficient (the generator is just
a small BERT-MLM).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.bert import BertConfig, BertLayer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear


@dataclass
class ElectraConfig(BertConfig):
    vocab_size: int = 30522
    embedding_size: int = 128

    @staticmethod
    def tiny(**kw):
        return ElectraConfig(**{**dict(vocab_size=128, hidden_size=32,
                                       embedding_size=16,
                                       num_hidden_layers=2,
                                       num_attention_heads=2,
                                       intermediate_size=64,
                                       max_position_embeddings=64), **kw})


class ElectraModel(Module):
    def __init__(self, cfg: ElectraConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        e = cfg.embedding_size
        self.word_embeddings = Embedding(cfg.vocab_size, e,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, e,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, e,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(e, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.embeddings_project = (Linear(e, cfg.hidden_size,
                                          dtype=cfg.dtype)
                                   if e != cfg.hidden_size else None)
        self.layers = [BertLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 rng=None):
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(s)[None, :])
             + self.token_type_embeddings(token_type_ids))
        x = self.dropout(self.emb_norm(x), rng=rng)
        if self.embeddings_project is not None:
            x = self.embeddings_project(x)
        for i, lyr in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = lyr(x, attn_mask=attention_mask, rng=sub)
        return x


class ElectraForPreTraining(Module):
    """Replaced-token-detection discriminator: [B, S] logits (>0 =
    predicted replaced)."""

    def __init__(self, cfg: ElectraConfig):
        super().__init__()
        self.cfg = cfg
        self.electra = ElectraModel(cfg)
        self.disc_dense = Linear(cfg.hidden_size, cfg.hidden_size,
                                 dtype=cfg.dtype)
        self.disc_out = Linear(cfg.hidden_size, 1, dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 rng=None):
        seq = self.electra(input_ids, token_type_ids, attention_mask,
                           rng=rng)
        return self.disc_out(F.gelu(self.disc_dense(seq)))[..., 0]

    def loss(self, input_ids, labels, token_type_ids=None,
             attention_mask=None, rng=None):
        """Per-token binary cross-entropy; labels -100 = ignored."""
        logits = self(input_ids, token_type_ids, attention_mask,
                      rng=rng).astype(jnp.float32)
        valid = (labels >= 0).astype(jnp.float32)
        y = jnp.clip(labels, 0, 1).astype(jnp.float32)
        ce = jnp.maximum(logits, 0) - logits * y + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)
