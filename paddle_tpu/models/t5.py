"""T5 encoder-decoder (ref capability: PaddleNLP ``paddlenlp.transformers.t5``
— T5ForConditionalGeneration; architecture per the public T5 paper).

TPU-native points:
  * relative position bias computed once per (q_len, k_len) as a static
    bucketed lookup — one gather + transpose, no per-step recompute;
  * encoder and decoder stacks share one layer implementation driven by a
    ``causal``/``cross`` flag; RMSNorm (T5 layer norm has no bias/mean);
  * everything jits; greedy seq2seq decode loop included.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"   # "relu" (v1.0) | "gated-gelu" (v1.1)
    tie_word_embeddings: bool = True  # v1.1 checkpoints untie the head
    dtype: object = None
    pad_token_id: int = 0
    decoder_start_token_id: int = 0
    # "ring" | "ulysses": self-attention over an sp-sharded sequence; the
    # LEARNED relative position bias rides the sp additive-bias path
    # (cross-attention stays local — mismatched q/k lengths)
    sequence_parallel: str | None = None

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()
        if self.feed_forward_proj not in ("relu", "gated-gelu"):
            raise ValueError(
                f"feed_forward_proj={self.feed_forward_proj!r} not supported "
                "(use 'relu' for v1.0 or 'gated-gelu' for v1.1)")

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                    num_decoder_layers=2, num_heads=4, dtype=jnp.float32)
        base.update(kw)
        return T5Config(**base)


class T5LayerNorm(Module):
    """RMS-style norm, no bias/mean subtraction (T5 convention)."""

    def __init__(self, d, eps, dtype):
        super().__init__()
        self.weight = I.Constant(1.0)((d,), dtype)
        self.eps = eps

    def __call__(self, x):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)) * self.weight


def _relative_position_bucket(rel_pos, bidirectional, num_buckets, max_distance):
    """Static bucket mapping (log-spaced beyond num_buckets//2)."""
    ret = 0
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(Module):
    def __init__(self, cfg: T5Config, has_relative_bias=False, bidirectional=True):
        super().__init__()
        d, h, kv = cfg.d_model, cfg.num_heads, cfg.d_kv
        init = I.Normal(0.0, (d * kv) ** -0.5)
        self.q = init((d, h * kv), cfg.dtype)
        self.k = I.Normal(0.0, d ** -0.5)((d, h * kv), cfg.dtype)
        self.v = I.Normal(0.0, d ** -0.5)((d, h * kv), cfg.dtype)
        self.o = I.Normal(0.0, (h * kv) ** -0.5)((h * kv, d), cfg.dtype)
        if has_relative_bias:
            self.rel_bias = I.Normal(0.0, 1.0)(
                (cfg.relative_attention_num_buckets, h), jnp.float32)
        else:
            self.rel_bias = None
        self.num_heads, self.d_kv = h, kv
        self.bidirectional = bidirectional
        self.num_buckets = cfg.relative_attention_num_buckets
        self.max_distance = cfg.relative_attention_max_distance
        self.sequence_parallel = cfg.sequence_parallel

    def position_bias(self, q_len, k_len):
        if self.rel_bias is None:
            return None
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = _relative_position_bucket(
            mem - ctx, self.bidirectional, self.num_buckets, self.max_distance)
        bias = jnp.take(self.rel_bias, buckets, axis=0)  # [q, k, h]
        return jnp.transpose(bias, (2, 0, 1))[None]  # [1, h, q, k]

    def __call__(self, x, kv=None, mask=None, position_bias=None, causal=False):
        b, s, _ = x.shape
        src = x if kv is None else kv
        sk = src.shape[1]
        h, dkv = self.num_heads, self.d_kv
        q = (x @ self.q).reshape(b, s, h, dkv)
        k = (src @ self.k).reshape(b, sk, h, dkv)
        v = (src @ self.v).reshape(b, sk, h, dkv)
        # sequence parallelism (self-attention only: cross-attention has
        # mismatched q/k lengths and stays local) — the relative position
        # bias rides the sp ADDITIVE-BIAS path, T5's unscaled scores via
        # scale=1.0
        if self.sequence_parallel in ("ring", "ulysses") and kv is None:
            from paddle_tpu.distributed.mesh import current_mesh
            mesh = current_mesh()
            if mesh is not None and mesh.size("sp") > 1:
                from paddle_tpu.distributed.sp import sp_attention
                mask3 = None
                if mask is not None:
                    mask3 = jnp.broadcast_to(
                        mask.astype(bool)[:, None, :], (b, s, sk))
                out = sp_attention(mesh, self.sequence_parallel, q, k, v,
                                   causal=causal, scale=1.0,
                                   attn_mask=mask3,
                                   attn_bias=position_bias)
                return out.reshape(b, s, h * dkv) @ self.o
        # T5: NO 1/sqrt(d) scaling (folded into init)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias
        if causal:
            cm = jnp.tril(jnp.ones((s, sk), bool))
            scores = jnp.where(cm[None, None], scores, -1e9)
        if mask is not None:
            scores = jnp.where(mask[:, None, None, :].astype(bool), scores, -1e9)
        w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, s, h * dkv)
        return out @ self.o


class T5FF(Module):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.gated = cfg.feed_forward_proj.startswith("gated")
        init_i = I.Normal(0.0, cfg.d_model ** -0.5)
        if self.gated:  # v1.1: wi_0 (gate, gelu) * wi_1, fused into one matmul
            self.wi = init_i((cfg.d_model, 2 * cfg.d_ff), cfg.dtype)
        else:
            self.wi = init_i((cfg.d_model, cfg.d_ff), cfg.dtype)
        self.wo = I.Normal(0.0, cfg.d_ff ** -0.5)((cfg.d_ff, cfg.d_model), cfg.dtype)

    def __call__(self, x):
        h = x @ self.wi
        if self.gated:
            gate, up = jnp.split(h, 2, axis=-1)
            # HF NewGELUActivation == tanh-approximate gelu
            h = jax.nn.gelu(gate, approximate=True) * up
        else:
            h = jax.nn.relu(h)
        return h @ self.wo


class T5Block(Module):
    def __init__(self, cfg: T5Config, is_decoder: bool, has_relative_bias: bool):
        super().__init__()
        self.is_decoder = is_decoder
        self.ln1 = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon, cfg.dtype)
        self.attn = T5Attention(cfg, has_relative_bias,
                                bidirectional=not is_decoder)
        if is_decoder:
            self.ln_cross = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon, cfg.dtype)
            self.cross_attn = T5Attention(cfg, False)
        self.ln2 = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon, cfg.dtype)
        self.ff = T5FF(cfg)

    def __call__(self, x, mask=None, enc=None, enc_mask=None, position_bias=None):
        x = x + self.attn(self.ln1(x), mask=mask, position_bias=position_bias,
                          causal=self.is_decoder)
        if self.is_decoder and enc is not None:
            x = x + self.cross_attn(self.ln_cross(x), kv=enc, mask=enc_mask)
        return x + self.ff(self.ln2(x))


class T5Stack(Module):
    def __init__(self, cfg: T5Config, is_decoder: bool, num_layers: int):
        super().__init__()
        self.blocks = [T5Block(cfg, is_decoder, has_relative_bias=(i == 0))
                       for i in range(num_layers)]
        self.final_norm = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon, cfg.dtype)

    def __call__(self, x, mask=None, enc=None, enc_mask=None):
        # bias computed once by block 0, shared down the stack (T5 scheme)
        pbias = self.blocks[0].attn.position_bias(x.shape[1], x.shape[1])
        for blk in self.blocks:
            x = blk(x, mask=mask, enc=enc, enc_mask=enc_mask, position_bias=pbias)
        return self.final_norm(x)


class T5Model(Module):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.shared = I.Normal(0.0, 1.0)((cfg.vocab_size, cfg.d_model), cfg.dtype)
        self.encoder = T5Stack(cfg, False, cfg.num_layers)
        self.decoder = T5Stack(cfg, True, cfg.num_decoder_layers)

    def encode(self, input_ids, attention_mask=None):
        x = jnp.take(self.shared, input_ids, axis=0)
        return self.encoder(x, mask=attention_mask)

    def decode(self, decoder_input_ids, enc, enc_mask=None):
        y = jnp.take(self.shared, decoder_input_ids, axis=0)
        return self.decoder(y, enc=enc, enc_mask=enc_mask)


class T5ForConditionalGeneration(Module):
    """Ref: paddlenlp.transformers.T5ForConditionalGeneration."""

    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.t5 = T5Model(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:  # v1.1: separate head, no rescale
            self.lm_head = I.Normal(0.0, cfg.d_model ** -0.5)(
                (cfg.d_model, cfg.vocab_size), cfg.dtype)

    def _project(self, hidden):
        if self.lm_head is None:
            # tied embedding head with T5's rescale
            return (hidden * (self.cfg.d_model ** -0.5)) @ self.t5.shared.T
        return hidden @ self.lm_head

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None):
        enc = self.t5.encode(input_ids, attention_mask)
        hidden = self.t5.decode(decoder_input_ids, enc, attention_mask)
        return self._project(hidden)

    def loss(self, input_ids, labels, attention_mask=None):
        """Teacher-forced seq2seq loss; decoder inputs = labels shifted right."""
        cfg = self.cfg
        start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id,
                         labels.dtype)
        dec_in = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
        logits = self(input_ids, dec_in, attention_mask)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.maximum(labels, 0)
        ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)

    def generate(self, input_ids, max_new_tokens=20, attention_mask=None,
                 eos_token_id=1):
        """Greedy seq2seq decode (static shapes; encoder runs once)."""
        cfg = self.cfg
        b = input_ids.shape[0]
        enc = self.t5.encode(input_ids, attention_mask)
        tokens = jnp.full((b, max_new_tokens + 1), cfg.decoder_start_token_id,
                          jnp.int32)

        def body(i, state):
            tokens, done = state
            hidden = self.t5.decode(tokens[:, :max_new_tokens + 1], enc,
                                    attention_mask)
            # project ONLY step i into the vocab (the [b, L, vocab] matmul
            # would be ~L× wasted MXU work per decode step)
            h_i = jax.lax.dynamic_slice_in_dim(hidden, i, 1, axis=1)[:, 0]
            step_logits = self._project(h_i)
            nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
            tokens = tokens.at[:, i + 1].set(nxt)
            return tokens, done

        done = jnp.zeros((b,), bool)
        tokens, _ = jax.lax.fori_loop(0, max_new_tokens, body, (tokens, done))
        return tokens[:, 1:]
