"""Whisper (ref: PaddleSpeech/PaddleNLP ``whisper`` — speech-to-text
seq2seq over log-mel spectrograms).

Encoder: two gelu Conv1Ds (the second stride-2) over the [B, mels, T]
input, fixed sinusoidal positions (stored as weights), pre-LN blocks,
final LN. Decoder: learned positions, pre-LN blocks with cross-attention
over the audio memory, final LN, head tied to the token embeddings.
Whisper's attention quirk — k_proj has no bias — loads as a zero bias.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class WhisperConfig:
    vocab_size: int = 51865
    num_mel_bins: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    decoder_layers: int = 4
    encoder_attention_heads: int = 6
    decoder_attention_heads: int = 6
    encoder_ffn_dim: int = 1536
    decoder_ffn_dim: int = 1536
    max_source_positions: int = 1500
    max_target_positions: int = 448
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return WhisperConfig(**{**dict(vocab_size=96, num_mel_bins=8,
                                       d_model=32, encoder_layers=2,
                                       decoder_layers=2,
                                       encoder_attention_heads=4,
                                       decoder_attention_heads=4,
                                       encoder_ffn_dim=64,
                                       decoder_ffn_dim=64,
                                       max_source_positions=16,
                                       max_target_positions=32), **kw})


class WhisperEncoderLayer(Module):
    def __init__(self, cfg: WhisperConfig):
        super().__init__()
        d = cfg.d_model
        self.self_attn = MultiHeadAttention(d, cfg.encoder_attention_heads,
                                            dtype=cfg.dtype)
        self.self_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.fc1 = Linear(d, cfg.encoder_ffn_dim, dtype=cfg.dtype)
        self.fc2 = Linear(cfg.encoder_ffn_dim, d, dtype=cfg.dtype)
        self.final_layer_norm = LayerNorm(d, dtype=cfg.dtype)

    def __call__(self, x):
        x = x + self.self_attn(self.self_attn_layer_norm(x))
        return x + self.fc2(F.gelu(self.fc1(self.final_layer_norm(x))))


class WhisperDecoderLayer(Module):
    def __init__(self, cfg: WhisperConfig):
        super().__init__()
        d = cfg.d_model
        self.self_attn = MultiHeadAttention(d, cfg.decoder_attention_heads,
                                            dtype=cfg.dtype)
        self.self_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.encoder_attn = MultiHeadAttention(d,
                                               cfg.decoder_attention_heads,
                                               dtype=cfg.dtype)
        self.encoder_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.fc1 = Linear(d, cfg.decoder_ffn_dim, dtype=cfg.dtype)
        self.fc2 = Linear(cfg.decoder_ffn_dim, d, dtype=cfg.dtype)
        self.final_layer_norm = LayerNorm(d, dtype=cfg.dtype)

    def __call__(self, x, enc):
        x = x + self.self_attn(self.self_attn_layer_norm(x), is_causal=True)
        x = x + self.encoder_attn(self.encoder_attn_layer_norm(x), enc, enc)
        return x + self.fc2(F.gelu(self.fc1(self.final_layer_norm(x))))


class WhisperForConditionalGeneration(Module):
    def __init__(self, cfg: WhisperConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        d = cfg.d_model
        # encoder conv front-end: [k, in, out] (NWC/WIO)
        self.conv1 = init((3, cfg.num_mel_bins, d), cfg.dtype)
        self.conv1_bias = jnp.zeros((d,), cfg.dtype)
        self.conv2 = init((3, d, d), cfg.dtype)
        self.conv2_bias = jnp.zeros((d,), cfg.dtype)
        self.enc_positions = init((cfg.max_source_positions, d), cfg.dtype)
        self.encoder_layers_m = [WhisperEncoderLayer(cfg)
                                 for _ in range(cfg.encoder_layers)]
        self.enc_final_norm = LayerNorm(d, dtype=cfg.dtype)

        self.embed_tokens = init((cfg.vocab_size, d), cfg.dtype)
        self.dec_positions = init((cfg.max_target_positions, d), cfg.dtype)
        self.decoder_layers_m = [WhisperDecoderLayer(cfg)
                                 for _ in range(cfg.decoder_layers)]
        self.dec_final_norm = LayerNorm(d, dtype=cfg.dtype)

    def encode(self, input_features):
        """input_features: [B, mels, T] (the reference layout)."""
        x = jnp.transpose(input_features, (0, 2, 1))        # NWC
        x = jax.lax.conv_general_dilated(
            x, self.conv1, (1,), [(1, 1)],
            dimension_numbers=("NWC", "WIO", "NWC")) + self.conv1_bias
        x = jax.nn.gelu(x)
        x = jax.lax.conv_general_dilated(
            x, self.conv2, (2,), [(1, 1)],
            dimension_numbers=("NWC", "WIO", "NWC")) + self.conv2_bias
        x = jax.nn.gelu(x)
        x = x + self.enc_positions[: x.shape[1]][None]
        for lyr in self.encoder_layers_m:
            x = lyr(x)
        return self.enc_final_norm(x)

    def __call__(self, input_features, decoder_input_ids):
        enc = self.encode(input_features)
        s = decoder_input_ids.shape[1]
        x = (jnp.take(self.embed_tokens, decoder_input_ids, axis=0)
             + self.dec_positions[:s][None])
        for lyr in self.decoder_layers_m:
            x = lyr(x, enc)
        x = self.dec_final_norm(x)
        return x @ self.embed_tokens.T       # proj_out tied

    def loss(self, input_features, decoder_input_ids, labels):
        logits = self(input_features, decoder_input_ids).astype(jnp.float32)
        ce = F.cross_entropy(logits, jnp.maximum(labels, 0),
                             reduction="none")
        mask = (labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
