"""Mixtral (ref capability: PaddleNLP ``mixtral`` model family —
Mixtral-8x7B-class sparse MoE).

LLaMA attention (GQA, optional sliding window, no biases) with every MLP
a routed-expert block: softmax -> top-k -> RENORMALISED gates (unlike
Qwen2-MoE's raw mass), no shared expert. Runs on the same sort-based
``distributed.moe.MoELayer`` in dropless mode; HF checkpoint parity in
tests/test_convert.py.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.distributed.moe import MoELayer
from paddle_tpu.models.llama import (LlamaAttention, LlamaConfig,
                                     LlamaRMSNorm)
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops import attention as A


@dataclass
class MixtralConfig(LlamaConfig):
    rms_norm_eps: float = 1e-5
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02

    @staticmethod
    def tiny(**kw):
        return MixtralConfig(**{**dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            num_local_experts=4, num_experts_per_tok=2,
            dtype=jnp.float32, remat=False, scan_layers=False), **kw})


class MixtralDecoderLayer(Module):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg.hidden_size,
                                            cfg.rms_norm_eps, cfg.dtype)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(
            cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)
        self.moe = MoELayer(cfg.hidden_size, cfg.intermediate_size,
                            cfg.num_local_experts,
                            k=cfg.num_experts_per_tok,
                            capacity_factor=None,      # dropless (exact)
                            norm_topk_prob=True,       # Mixtral renorms
                            dtype=cfg.dtype)

    def __call__(self, x, cos, sin, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        y, aux = self.moe(self.post_attention_layernorm(x))
        return x + y, aux


class MixtralForCausalLM(Module):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size),
                                 cfg.dtype)
        self.layers = [MixtralDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                                 cfg.dtype)
        self.lm_head = init((cfg.hidden_size, cfg.vocab_size), cfg.dtype)

    def _forward(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = A.rope_cos_sin(
            s, d, base=cfg.rope_theta, scaling=cfg.rope_scaling,
            max_position_embeddings=cfg.max_position_embeddings)
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        aux_total = 0.0
        for lyr in self.layers:
            x, aux = lyr(x, cos, sin)
            aux_total = aux_total + aux
        from paddle_tpu.quantization import wo_matmul
        return wo_matmul(self.norm(x), self.lm_head), aux_total

    def __call__(self, input_ids):
        return self._forward(input_ids)[0]

    def loss(self, input_ids, labels):
        from paddle_tpu.nn import functional as F
        logits, aux = self._forward(input_ids)
        ce = F.cross_entropy(logits.astype(jnp.float32),
                             jnp.maximum(labels, 0), reduction="none")
        mask = (labels >= 0).astype(jnp.float32)
        lm = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return lm + self.cfg.router_aux_loss_coef * aux
