"""BERT (ref: PaddleNLP ``paddlenlp/transformers/bert/modeling.py`` and the
reference's Fleet data-parallel BERT pretraining config in BASELINE.json).

TPU-first: post-LN encoder stack with fused attention dispatch; MLM+NSP
pretraining heads; batch rides the (dp, fsdp) axes — pure data parallel is
just the mesh with tp=1.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32
    # padded-varlen attention: interpret attention_mask as a CONTIGUOUS
    # prefix (standard right-padding) and pass per-row lengths to the fused
    # flash kernel instead of a dense additive mask (which forces the XLA
    # fallback). Ref: flash_attn varlen / PaddleNLP padded-batch pretraining.
    varlen_attention: bool = False

    @staticmethod
    def base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def large(**kw):
        return BertConfig(**{**dict(hidden_size=1024, num_hidden_layers=24,
                                    num_attention_heads=16, intermediate_size=4096), **kw})

    @staticmethod
    def tiny(**kw):
        return BertConfig(**{**dict(vocab_size=128, hidden_size=32,
                                    num_hidden_layers=2, num_attention_heads=2,
                                    intermediate_size=64, max_position_embeddings=64,
                                    type_vocab_size=2), **kw})


class BertEmbeddings(Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                             weight_init=init, dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                               weight_init=init, dtype=cfg.dtype)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def __call__(self, input_ids, token_type_ids=None, position_ids=None, rng=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x), rng=rng)


class BertLayer(Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = MultiHeadAttention(cfg.hidden_size, cfg.num_attention_heads,
                                            dropout=cfg.attention_probs_dropout_prob,
                                            dtype=cfg.dtype)
        self.attn_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.intermediate = Linear(cfg.hidden_size, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, cfg.hidden_size, dtype=cfg.dtype)
        self.out_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def __call__(self, x, attn_mask=None, rng=None, kv_lens=None):
        # three INDEPENDENT dropout draws: attention-internal, post-attn
        # residual, post-FF residual
        r1, r2, r3 = ((None,) * 3 if rng is None
                      else tuple(jax.random.split(rng, 3)))
        h = self.attention(x, attn_mask=attn_mask, rng=r1, kv_lens=kv_lens)
        x = self.attn_norm(x + self.dropout(h, rng=r2))
        h = self.output(F.gelu(self.intermediate(x)))
        return self.out_norm(x + self.dropout(h, rng=r3))


class BertModel(Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = [BertLayer(cfg) for _ in range(cfg.num_hidden_layers)]
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size, dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 rng=None, position_ids=None):
        kv_lens = None
        if attention_mask is not None:
            if self.cfg.varlen_attention:
                # contiguous right-padding only: lengths keep the fused
                # kernel. Guard eagerly-passed masks (a traced mask inside
                # jit cannot be checked — the contract is documented).
                if not isinstance(attention_mask, jax.core.Tracer):
                    am = np.asarray(attention_mask)
                    lens_np = am.sum(axis=1)
                    prefix = (np.arange(am.shape[1])[None, :]
                              < lens_np[:, None]).astype(am.dtype)
                    if not np.array_equal(am, prefix):
                        raise ValueError(
                            "varlen_attention=True requires a CONTIGUOUS "
                            "right-padded attention_mask (1s then 0s); got "
                            "a non-prefix mask — use varlen_attention="
                            "False for arbitrary masks")
                kv_lens = jnp.sum(attention_mask.astype(jnp.int32), axis=1)
                attention_mask = None
            else:
                # [B, S] 1/0 -> additive mask [B, 1, 1, S]
                attention_mask = (1.0 - attention_mask[:, None, None, :]
                                  .astype(jnp.float32)) * -1e9
        x = self.embeddings(input_ids, token_type_ids,
                            position_ids=position_ids, rng=rng)
        for i, lyr in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = lyr(x, attn_mask=attention_mask, rng=sub, kv_lens=kv_lens)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(Module):
    """MLM + NSP heads (ref BertForPretraining)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size, dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)
        self.nsp_head = Linear(cfg.hidden_size, 2, dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, rng=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask, rng=rng)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        mlm_logits = h @ self.bert.embeddings.word_embeddings.weight.T + self.mlm_bias
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    def loss(self, input_ids, mlm_labels, nsp_labels=None, token_type_ids=None,
             attention_mask=None, rng=None):
        mlm_logits, nsp_logits = self(input_ids, token_type_ids, attention_mask, rng=rng)
        mlm = F.cross_entropy(mlm_logits, jnp.maximum(mlm_labels, 0), reduction="none")
        mask = (mlm_labels >= 0).astype(jnp.float32)
        loss = jnp.sum(mlm * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss


class BertForSequenceClassification(Module):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.classifier = Linear(cfg.hidden_size, num_classes, dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, rng=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask, rng=rng)
        return self.classifier(self.dropout(pooled, rng=rng))
