"""CLIP (ref: PaddleNLP ``paddlenlp/transformers/clip`` / PaddleMIX —
contrastive image-text pretraining).

Dual-tower contrastive model: a ViT-style vision tower (patch conv +
class token + learned positions, pre-LN, post-LN pooled class token) and
a CAUSAL text tower (quick-gelu MLPs, pooled at the EOS position), each
projected into the shared embedding space; similarity logits scale by a
learned temperature. HF ``CLIPModel`` is the parity reference.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.ops import attention as A


@dataclass
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_hidden_layers: int = 12
    num_attention_heads: int = 8
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    eos_token_id: int = 49407


@dataclass
class CLIPVisionConfig:
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    image_size: int = 224
    patch_size: int = 32
    num_channels: int = 3
    layer_norm_eps: float = 1e-5


@dataclass
class CLIPConfig:
    text_config: CLIPTextConfig = None
    vision_config: CLIPVisionConfig = None
    projection_dim: int = 512
    logit_scale_init_value: float = 2.6592
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.text_config is None:
            self.text_config = CLIPTextConfig()
        if self.vision_config is None:
            self.vision_config = CLIPVisionConfig()

    @staticmethod
    def tiny(**kw):
        return CLIPConfig(**{**dict(
            text_config=CLIPTextConfig(vocab_size=96, hidden_size=32,
                                       intermediate_size=64,
                                       num_hidden_layers=2,
                                       num_attention_heads=4,
                                       max_position_embeddings=16,
                                       eos_token_id=1),
            vision_config=CLIPVisionConfig(hidden_size=32,
                                           intermediate_size=64,
                                           num_hidden_layers=2,
                                           num_attention_heads=4,
                                           image_size=32, patch_size=8),
            projection_dim=16), **kw})


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


class CLIPEncoderLayer(Module):
    """Pre-LN block with quick-gelu MLP, shared by both towers."""

    def __init__(self, h, inter, heads, eps, dtype):
        super().__init__()
        self.layer_norm1 = LayerNorm(h, epsilon=eps, dtype=dtype)
        self.q_proj = Linear(h, h, dtype=dtype)
        self.k_proj = Linear(h, h, dtype=dtype)
        self.v_proj = Linear(h, h, dtype=dtype)
        self.out_proj = Linear(h, h, dtype=dtype)
        self.layer_norm2 = LayerNorm(h, epsilon=eps, dtype=dtype)
        self.fc1 = Linear(h, inter, dtype=dtype)
        self.fc2 = Linear(inter, h, dtype=dtype)
        self.heads = heads

    def __call__(self, x, causal=False):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        h = self.layer_norm1(x)
        q = self.q_proj(h).reshape(b, s, nh, d)
        k = self.k_proj(h).reshape(b, s, nh, d)
        v = self.v_proj(h).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, is_causal=causal)
        x = x + self.out_proj(att.reshape(b, s, hd))
        return x + self.fc2(_quick_gelu(self.fc1(self.layer_norm2(x))))


class CLIPTextModel(Module):
    def __init__(self, cfg: CLIPConfig):
        super().__init__()
        t = cfg.text_config
        init = I.Normal(0.0, cfg.initializer_range)
        self.token_embedding = Embedding(t.vocab_size, t.hidden_size,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embedding = Embedding(t.max_position_embeddings,
                                            t.hidden_size,
                                            weight_init=init,
                                            dtype=cfg.dtype)
        self.layers = [CLIPEncoderLayer(t.hidden_size, t.intermediate_size,
                                        t.num_attention_heads,
                                        t.layer_norm_eps, cfg.dtype)
                       for _ in range(t.num_hidden_layers)]
        self.final_layer_norm = LayerNorm(t.hidden_size,
                                          epsilon=t.layer_norm_eps,
                                          dtype=cfg.dtype)
        self.eos_token_id = t.eos_token_id

    def __call__(self, input_ids):
        s = input_ids.shape[1]
        x = (self.token_embedding(input_ids)
             + self.position_embedding(jnp.arange(s)[None, :]))
        for lyr in self.layers:
            x = lyr(x, causal=True)           # CLIP text is CAUSAL
        x = self.final_layer_norm(x)
        # pooled feature = hidden state at the (first) EOS position
        eos_pos = jnp.argmax(
            (input_ids == self.eos_token_id).astype(jnp.int32), axis=1)
        pooled = x[jnp.arange(x.shape[0]), eos_pos]
        return x, pooled


class CLIPVisionModel(Module):
    def __init__(self, cfg: CLIPConfig):
        super().__init__()
        v = cfg.vision_config
        init = I.Normal(0.0, cfg.initializer_range)
        h = v.hidden_size
        self.patch_embedding = init(
            (v.patch_size, v.patch_size, v.num_channels, h), cfg.dtype)
        self.class_embedding = init((h,), cfg.dtype)
        n_patches = (v.image_size // v.patch_size) ** 2
        self.position_embedding = Embedding(n_patches + 1, h,
                                            weight_init=init,
                                            dtype=cfg.dtype)
        self.pre_layrnorm = LayerNorm(h, epsilon=v.layer_norm_eps,
                                      dtype=cfg.dtype)
        self.layers = [CLIPEncoderLayer(h, v.intermediate_size,
                                        v.num_attention_heads,
                                        v.layer_norm_eps, cfg.dtype)
                       for _ in range(v.num_hidden_layers)]
        self.post_layernorm = LayerNorm(h, epsilon=v.layer_norm_eps,
                                        dtype=cfg.dtype)
        self.patch = v.patch_size

    def __call__(self, pixel_values):
        """pixel_values: [B, C, H, W] (the reference layout)."""
        b = pixel_values.shape[0]
        x = jnp.transpose(pixel_values, (0, 2, 3, 1))       # NHWC
        x = jax.lax.conv_general_dilated(
            x, self.patch_embedding, (self.patch, self.patch), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = x.reshape(b, -1, x.shape[-1])                   # [B, P, H]
        cls = jnp.broadcast_to(self.class_embedding[None, None],
                               (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + self.position_embedding(
            jnp.arange(x.shape[1])[None, :])
        x = self.pre_layrnorm(x)
        for lyr in self.layers:
            x = lyr(x)
        pooled = self.post_layernorm(x[:, 0])
        return x, pooled


class CLIPModel(Module):
    def __init__(self, cfg: CLIPConfig):
        super().__init__()
        self.cfg = cfg
        self.text_model = CLIPTextModel(cfg)
        self.vision_model = CLIPVisionModel(cfg)
        self.visual_projection = Linear(cfg.vision_config.hidden_size,
                                        cfg.projection_dim,
                                        bias_attr=False, dtype=cfg.dtype)
        self.text_projection = Linear(cfg.text_config.hidden_size,
                                      cfg.projection_dim,
                                      bias_attr=False, dtype=cfg.dtype)
        self.logit_scale = jnp.asarray(cfg.logit_scale_init_value,
                                       cfg.dtype)

    def get_text_features(self, input_ids):
        _, pooled = self.text_model(input_ids)
        return self.text_projection(pooled)

    def get_image_features(self, pixel_values):
        _, pooled = self.vision_model(pixel_values)
        return self.visual_projection(pooled)

    def __call__(self, input_ids, pixel_values):
        """Returns (logits_per_image, logits_per_text)."""
        te = self.get_text_features(input_ids)
        ie = self.get_image_features(pixel_values)
        te = te / jnp.linalg.norm(te, axis=-1, keepdims=True)
        ie = ie / jnp.linalg.norm(ie, axis=-1, keepdims=True)
        scale = jnp.exp(self.logit_scale)
        logits_per_text = (te @ ie.T) * scale
        return logits_per_text.T, logits_per_text
