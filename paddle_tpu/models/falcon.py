"""Falcon decoder LM (ref capability: PaddleNLP/FlagAI Falcon family —
``tiiuae/falcon-*`` checkpoints; HF ``FalconForCausalLM`` is the parity
reference).

The multi-query member of the model zoo: falcon-7b runs ONE shared K/V
head (multi_query) under a single-LN parallel block (attention and MLP
both read ``input_layernorm(x)``); the 40b/180b "new decoder
architecture" runs grouped K/V heads with separate ``ln_attn``/``ln_mlp``.
Rotary is LLaMA-style rotate-half over the full head dim; the falcon-rw
variants use ALiBi instead (BLOOM's slope schedule) with sequential
residuals. All variants share tied word embeddings.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.models.bloom import alibi_slopes
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_hidden_layers: int = 32
    num_attention_heads: int = 71
    num_kv_heads: int = None         # new_decoder_architecture only
    new_decoder_architecture: bool = False
    multi_query: bool = True
    parallel_attn: bool = True
    bias: bool = False
    alibi: bool = False
    rope_theta: float = 10000.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()

    @property
    def kv_heads(self):
        if self.new_decoder_architecture:
            return self.num_kv_heads or self.num_attention_heads
        return 1 if self.multi_query else self.num_attention_heads

    @staticmethod
    def tiny(**kw):
        return FalconConfig(**{**dict(vocab_size=128, hidden_size=32,
                                      num_hidden_layers=2,
                                      num_attention_heads=4,
                                      dtype=jnp.float32, remat=False),
                               **kw})


class FalconDecoderLayer(Module):
    def __init__(self, cfg: FalconConfig):
        super().__init__()
        h = cfg.hidden_size
        nkv = cfg.kv_heads
        d = h // cfg.num_attention_heads
        init = I.Normal(0.0, cfg.initializer_range)
        eps = cfg.layer_norm_epsilon
        if cfg.new_decoder_architecture:
            self.ln_attn = LayerNorm(h, epsilon=eps, dtype=cfg.dtype)
            self.ln_mlp = LayerNorm(h, epsilon=eps, dtype=cfg.dtype)
            self.input_layernorm = None
            self.post_attention_layernorm = None
        else:
            self.input_layernorm = LayerNorm(h, epsilon=eps, dtype=cfg.dtype)
            self.ln_attn = self.ln_mlp = None
            self.post_attention_layernorm = (
                None if cfg.parallel_attn
                else LayerNorm(h, epsilon=eps, dtype=cfg.dtype))
        self.wq = init((h, h), cfg.dtype)
        self.wk = init((h, nkv * d), cfg.dtype)
        self.wv = init((h, nkv * d), cfg.dtype)
        self.dense = init((h, h), cfg.dtype)
        zb = (lambda n: jnp.zeros((n,), cfg.dtype)) if cfg.bias else \
            (lambda n: None)
        self.wq_bias, self.wk_bias = zb(h), zb(nkv * d)
        self.wv_bias, self.dense_bias = zb(nkv * d), zb(h)
        self.h_to_4h = init((h, 4 * h), cfg.dtype)
        self.four_h_to_h = init((4 * h, h), cfg.dtype)
        self.h_to_4h_bias, self.four_h_to_h_bias = zb(4 * h), zb(h)
        self.cfg_ref = (cfg.num_attention_heads, nkv, cfg.parallel_attn,
                        cfg.alibi)

    def _proj(self, x, w, b):
        y = x @ w
        return y if b is None else y + b

    def _attn(self, h, cos, sin, slopes):
        b, s, hd = h.shape
        nh, nkv, _, alibi = self.cfg_ref
        d = hd // nh
        q = self._proj(h, self.wq, self.wq_bias).reshape(b, s, nh, d)
        k = self._proj(h, self.wk, self.wk_bias).reshape(b, s, nkv, d)
        v = self._proj(h, self.wv, self.wv_bias).reshape(b, s, nkv, d)
        if not alibi:
            q, k = A.apply_rope(q, cos, sin), A.apply_rope(k, cos, sin)
        att = A.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            alibi_slopes=slopes if alibi else None)
        return self._proj(att.reshape(b, s, hd), self.dense,
                          self.dense_bias)

    def _mlp(self, h):
        m = jax.nn.gelu(self._proj(h, self.h_to_4h, self.h_to_4h_bias),
                        approximate=False)
        return self._proj(m, self.four_h_to_h, self.four_h_to_h_bias)

    def __call__(self, x, cos, sin, slopes):
        _, _, parallel, _ = self.cfg_ref
        if self.ln_attn is not None:        # new decoder architecture
            return (x + self._attn(self.ln_attn(x), cos, sin, slopes)
                    + self._mlp(self.ln_mlp(x)))
        h = self.input_layernorm(x)
        att = self._attn(h, cos, sin, slopes)
        if parallel:                        # 7b: ONE ln feeds attn and mlp
            return x + att + self._mlp(h)
        x = x + att                         # falcon-rw: sequential
        return x + self._mlp(self.post_attention_layernorm(x))


class FalconForCausalLM(Module):
    def __init__(self, cfg: FalconConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = init((cfg.vocab_size, cfg.hidden_size),
                                    cfg.dtype)
        self.h = [FalconDecoderLayer(cfg)
                  for _ in range(cfg.num_hidden_layers)]
        self.ln_f = LayerNorm(cfg.hidden_size,
                              epsilon=cfg.layer_norm_epsilon,
                              dtype=cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = A.rope_cos_sin(s, d, base=cfg.rope_theta)
        # Parity with HF transformers' Falcon: the model folds alibi/sqrt(d)
        # into the causal mask (FalconModel._update_causal_mask) AND the
        # eager attention adds alibi again before scaling by 1/sqrt(d)
        # ((scores + alibi) * inv_norm_factor) — the effective bias is
        # 2*m/sqrt(d). We reproduce the reference implementation's numbers,
        # double-add included (verified against tiny checkpoints in
        # tests/test_convert.py).
        slopes = (alibi_slopes(cfg.num_attention_heads) * (2.0 * d ** -0.5)
                  if cfg.alibi else None)
        x = jnp.take(self.word_embeddings, input_ids, axis=0)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin, slopes))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin, slopes)))
        for lyr in self.h:
            x = blk(lyr, x)
        x = self.ln_f(x)
        return x @ self.word_embeddings.T    # tied head

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
