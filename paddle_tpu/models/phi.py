"""Phi-1/1.5/2 decoder LM (ref capability: PaddleNLP ``phi`` family).

Single-LN parallel block (attention and MLP both read
``input_layernorm(x)`` and sum into one residual), LLaMA-style
rotate-half rope over the first ``partial_rotary_factor`` of each head
dim (GPT-NeoX pairing — unlike GPT-J's interleave), biased q/k/v/dense,
tanh-gelu MLP, untied biased head over a final LayerNorm.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.models.gpt_neox import _rope_partial
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2560
    intermediate_size: int = 10240
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = None
    partial_rotary_factor: float = 0.4
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @staticmethod
    def tiny(**kw):
        return PhiConfig(**{**dict(vocab_size=128, hidden_size=32,
                                   intermediate_size=64,
                                   num_hidden_layers=2,
                                   num_attention_heads=4,
                                   num_key_value_heads=2,
                                   partial_rotary_factor=0.5,
                                   max_position_embeddings=64,
                                   dtype=jnp.float32, remat=False), **kw})


class PhiDecoderLayer(Module):
    def __init__(self, cfg: PhiConfig):
        super().__init__()
        h = cfg.hidden_size
        nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        d = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        self.input_layernorm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                         dtype=cfg.dtype)
        self.qkv_proj = init((h, (nh + 2 * nkv) * d), cfg.dtype)
        self.qkv_bias = jnp.zeros(((nh + 2 * nkv) * d,), cfg.dtype)
        self.dense = init((h, h), cfg.dtype)
        self.dense_bias = jnp.zeros((h,), cfg.dtype)
        self.fc1 = init((h, cfg.intermediate_size), cfg.dtype)
        self.fc1_bias = jnp.zeros((cfg.intermediate_size,), cfg.dtype)
        self.fc2 = init((cfg.intermediate_size, h), cfg.dtype)
        self.fc2_bias = jnp.zeros((h,), cfg.dtype)
        self.dims = (nh, nkv, d, int(d * cfg.partial_rotary_factor))

    def __call__(self, x, cos, sin):
        b, s, hd = x.shape
        nh, nkv, d, rot = self.dims
        h = self.input_layernorm(x)          # ONE LN feeds attn AND mlp
        qkv = h @ self.qkv_proj + self.qkv_bias
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
        q = _rope_partial(q.reshape(b, s, nh, d), cos, sin, rot)
        k = _rope_partial(k.reshape(b, s, nkv, d), cos, sin, rot)
        att = A.scaled_dot_product_attention(q, k, v.reshape(b, s, nkv, d),
                                             is_causal=True)
        att = att.reshape(b, s, hd) @ self.dense + self.dense_bias
        m = jax.nn.gelu(h @ self.fc1 + self.fc1_bias, approximate=True)
        return x + att + (m @ self.fc2 + self.fc2_bias)


class PhiForCausalLM(Module):
    def __init__(self, cfg: PhiConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size),
                                 cfg.dtype)
        self.layers = [PhiDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.final_layernorm = LayerNorm(cfg.hidden_size,
                                         epsilon=cfg.layer_norm_eps,
                                         dtype=cfg.dtype)
        self.lm_head = init((cfg.hidden_size, cfg.vocab_size), cfg.dtype)
        self.lm_head_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        rot = int(d * cfg.partial_rotary_factor)
        cos, sin = A.rope_cos_sin(s, rot, base=cfg.rope_theta)
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin)))
        for lyr in self.layers:
            x = blk(lyr, x)
        x = self.final_layernorm(x)
        return x @ self.lm_head + self.lm_head_bias

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
