"""Qwen2-MoE (ref capability: PaddleNLP ``qwen2_moe`` modeling — the
Qwen1.5/2-MoE-A2.7B family).

The HF-checkpoint-compatible face of the MoE stack: Qwen2 attention
(biased fused QKV, GQA, rope 1e6) with every MLP replaced by a sparse
block = sort-based top-k routed experts (``distributed.moe.MoELayer`` in
dropless ``capacity_factor=None`` mode, ``norm_topk_prob`` per config —
Qwen defaults to NOT renormalising the top-k mass) PLUS a dense shared
expert scaled by a per-token sigmoid gate. Loading a real checkpoint
through ``load_qwen2_moe_state_dict`` and matching HF logits
(tests/test_convert.py) is the end-to-end proof that the expert-parallel
machinery computes the reference MoE math.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.distributed.moe import MoELayer, expert_mlp_apply
from paddle_tpu.models.llama import (LlamaAttention, LlamaConfig, LlamaMLP,
                                     LlamaRMSNorm)
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops import attention as A


@dataclass
class Qwen2MoeConfig(LlamaConfig):
    rms_norm_eps: float = 1e-6           # Qwen2-MoE convention (not 1e-5)
    num_experts: int = 60
    num_experts_per_tok: int = 4
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    norm_topk_prob: bool = False
    decoder_sparse_step: int = 1
    mlp_only_layers: tuple = ()
    router_aux_loss_coef: float = 0.001

    @staticmethod
    def tiny(**kw):
        return Qwen2MoeConfig(**{**dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            attention_bias=True, num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=48,
            dtype=jnp.float32, remat=False, scan_layers=False), **kw})


class Qwen2MoeSparseBlock(Module):
    """Routed experts + sigmoid-gated shared expert (HF
    Qwen2MoeSparseMoeBlock)."""

    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.moe = MoELayer(h, cfg.moe_intermediate_size, cfg.num_experts,
                            k=cfg.num_experts_per_tok,
                            capacity_factor=None,      # dropless (exact)
                            norm_topk_prob=cfg.norm_topk_prob,
                            dtype=cfg.dtype)
        self.shared_gate_up = init((h, 2 * cfg.shared_expert_intermediate_size),
                                   cfg.dtype)
        self.shared_down = init((cfg.shared_expert_intermediate_size, h),
                                cfg.dtype)
        self.shared_gate = init((h, 1), cfg.dtype)

    def __call__(self, x):
        y, aux = self.moe(x)
        shared = expert_mlp_apply(x[None] if x.ndim == 2 else x,
                                  self.shared_gate_up[None],
                                  self.shared_down[None])
        shared = shared if x.ndim == 3 else shared[0]
        sg = jax.nn.sigmoid(x @ self.shared_gate)
        return y + sg * shared, aux


class Qwen2MoeDecoderLayer(Module):
    def __init__(self, cfg: Qwen2MoeConfig, layer_idx: int):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg.hidden_size,
                                            cfg.rms_norm_eps, cfg.dtype)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(
            cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)
        sparse = (layer_idx not in tuple(cfg.mlp_only_layers)
                  and cfg.num_experts > 0
                  and (layer_idx + 1) % cfg.decoder_sparse_step == 0)
        self.mlp = Qwen2MoeSparseBlock(cfg) if sparse else LlamaMLP(cfg)
        self.sparse = sparse

    def __call__(self, x, cos, sin, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        h = self.post_attention_layernorm(x)
        if self.sparse:
            y, aux = self.mlp(h)
        else:
            y, aux = self.mlp(h), 0.0
        return x + y, aux


class Qwen2MoeForCausalLM(Module):
    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size),
                                 cfg.dtype)
        self.layers = [Qwen2MoeDecoderLayer(cfg, i)
                       for i in range(cfg.num_hidden_layers)]
        self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                                 cfg.dtype)
        self.lm_head = init((cfg.hidden_size, cfg.vocab_size), cfg.dtype)

    def _forward(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = A.rope_cos_sin(
            s, d, base=cfg.rope_theta,
            scaling=getattr(cfg, "rope_scaling", None),
            max_position_embeddings=cfg.max_position_embeddings)
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        aux_total = 0.0
        for lyr in self.layers:
            x, aux = lyr(x, cos, sin)
            aux_total = aux_total + aux
        from paddle_tpu.quantization import wo_matmul
        return wo_matmul(self.norm(x), self.lm_head), aux_total

    def __call__(self, input_ids):
        return self._forward(input_ids)[0]

    def loss(self, input_ids, labels):
        from paddle_tpu.nn import functional as F
        logits, aux = self._forward(input_ids)
        ce = F.cross_entropy(logits.astype(jnp.float32),
                             jnp.maximum(labels, 0), reduction="none")
        mask = (labels >= 0).astype(jnp.float32)
        lm = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return lm + self.cfg.router_aux_loss_coef * aux
