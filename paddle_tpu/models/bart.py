"""BART (ref: PaddleNLP ``paddlenlp/transformers/bart/modeling.py`` —
the denoising seq2seq family, also the mBART shape).

The POST-LN encoder-decoder of the zoo (T5 is pre-LN/relative-bias; BART
is post-LN/learned-positions): shared embeddings (optionally scaled by
sqrt(d)), learned positions at the fairseq +2 offset, an embedding
LayerNorm, decoder with cross-attention, and a tied LM head with a
``final_logits_bias`` buffer.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 768
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    max_position_embeddings: int = 1024
    pad_token_id: int = 1
    scale_embedding: bool = False
    # mBART shape: pre-LN layers + a final LN on encoder and decoder
    normalize_before: bool = False
    add_final_layer_norm: bool = False
    # fairseq heritage: BART/mBART position row p+2 holds position p;
    # Pegasus has no offset (and a STATIC sinusoidal table)
    position_offset: int = 2
    add_embedding_norm: bool = True      # Pegasus drops the embedding LN
    # Blenderbot-small quirk: the DECODER norms token embeds BEFORE
    # adding positions (encoder norms after, like BART)
    decoder_norm_before_pos: bool = False
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return BartConfig(**{**dict(vocab_size=128, d_model=32,
                                    encoder_layers=2, decoder_layers=2,
                                    encoder_attention_heads=4,
                                    decoder_attention_heads=4,
                                    encoder_ffn_dim=64, decoder_ffn_dim=64,
                                    max_position_embeddings=64), **kw})


class BartEncoderLayer(Module):
    def __init__(self, cfg: BartConfig):
        super().__init__()
        d = cfg.d_model
        self.self_attn = MultiHeadAttention(d, cfg.encoder_attention_heads,
                                            dtype=cfg.dtype)
        self.self_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.fc1 = Linear(d, cfg.encoder_ffn_dim, dtype=cfg.dtype)
        self.fc2 = Linear(cfg.encoder_ffn_dim, d, dtype=cfg.dtype)
        self.final_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.pre_norm = cfg.normalize_before

    def __call__(self, x, attn_mask=None):
        if self.pre_norm:                    # mBART
            x = x + self.self_attn(self.self_attn_layer_norm(x),
                                   attn_mask=attn_mask)
            return x + self.fc2(F.gelu(self.fc1(self.final_layer_norm(x))))
        x = self.self_attn_layer_norm(
            x + self.self_attn(x, attn_mask=attn_mask))
        return self.final_layer_norm(x + self.fc2(F.gelu(self.fc1(x))))


class BartDecoderLayer(Module):
    def __init__(self, cfg: BartConfig):
        super().__init__()
        d = cfg.d_model
        self.self_attn = MultiHeadAttention(d, cfg.decoder_attention_heads,
                                            dtype=cfg.dtype)
        self.self_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.encoder_attn = MultiHeadAttention(d,
                                               cfg.decoder_attention_heads,
                                               dtype=cfg.dtype)
        self.encoder_attn_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.fc1 = Linear(d, cfg.decoder_ffn_dim, dtype=cfg.dtype)
        self.fc2 = Linear(cfg.decoder_ffn_dim, d, dtype=cfg.dtype)
        self.final_layer_norm = LayerNorm(d, dtype=cfg.dtype)
        self.pre_norm = cfg.normalize_before

    def __call__(self, x, enc, enc_mask=None):
        if self.pre_norm:                    # mBART
            x = x + self.self_attn(self.self_attn_layer_norm(x),
                                   is_causal=True)
            x = x + self.encoder_attn(self.encoder_attn_layer_norm(x),
                                      enc, enc, attn_mask=enc_mask)
            return x + self.fc2(F.gelu(self.fc1(self.final_layer_norm(x))))
        x = self.self_attn_layer_norm(
            x + self.self_attn(x, is_causal=True))
        x = self.encoder_attn_layer_norm(
            x + self.encoder_attn(x, enc, enc, attn_mask=enc_mask))
        return self.final_layer_norm(x + self.fc2(F.gelu(self.fc1(x))))


class BartForConditionalGeneration(Module):
    def __init__(self, cfg: BartConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        d = cfg.d_model
        self.shared = init((cfg.vocab_size, d), cfg.dtype)
        # fairseq offset rows (positions p live at row p + offset)
        rows = cfg.max_position_embeddings + cfg.position_offset
        self.enc_positions = init((rows, d), cfg.dtype)
        self.dec_positions = init((rows, d), cfg.dtype)
        self.enc_layernorm_embedding = (LayerNorm(d, dtype=cfg.dtype)
                                        if cfg.add_embedding_norm else None)
        self.dec_layernorm_embedding = (LayerNorm(d, dtype=cfg.dtype)
                                        if cfg.add_embedding_norm else None)
        self.encoder_layers_m = [BartEncoderLayer(cfg)
                                 for _ in range(cfg.encoder_layers)]
        self.decoder_layers_m = [BartDecoderLayer(cfg)
                                 for _ in range(cfg.decoder_layers)]
        self.enc_final_norm = (LayerNorm(d, dtype=cfg.dtype)
                               if cfg.add_final_layer_norm else None)
        self.dec_final_norm = (LayerNorm(d, dtype=cfg.dtype)
                               if cfg.add_final_layer_norm else None)
        self.final_logits_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def _embed(self, ids, pos_table, norm, norm_before_pos=False):
        scale = (self.cfg.d_model ** 0.5 if self.cfg.scale_embedding
                 else 1.0)
        s = ids.shape[1]
        off = self.cfg.position_offset
        x = jnp.take(self.shared, ids, axis=0) * scale
        pos = pos_table[off: s + off][None]
        if norm_before_pos and norm is not None:
            return norm(x) + pos
        x = x + pos
        return norm(x) if norm is not None else x

    def encode(self, input_ids, attention_mask=None):
        mask = None
        if attention_mask is not None:
            mask = (1.0 - attention_mask[:, None, None, :]
                    .astype(jnp.float32)) * -1e9
        x = self._embed(input_ids, self.enc_positions,
                        self.enc_layernorm_embedding)
        for lyr in self.encoder_layers_m:
            x = lyr(x, attn_mask=mask)
        if self.enc_final_norm is not None:
            x = self.enc_final_norm(x)
        return x

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None):
        enc = self.encode(input_ids, attention_mask)
        enc_mask = None
        if attention_mask is not None:
            enc_mask = (1.0 - attention_mask[:, None, None, :]
                        .astype(jnp.float32)) * -1e9
        x = self._embed(decoder_input_ids, self.dec_positions,
                        self.dec_layernorm_embedding,
                        norm_before_pos=self.cfg.decoder_norm_before_pos)
        for lyr in self.decoder_layers_m:
            x = lyr(x, enc, enc_mask=enc_mask)
        if self.dec_final_norm is not None:
            x = self.dec_final_norm(x)
        return x @ self.shared.T + self.final_logits_bias

    def loss(self, input_ids, decoder_input_ids, labels,
             attention_mask=None):
        logits = self(input_ids, decoder_input_ids,
                      attention_mask).astype(jnp.float32)
        ce = F.cross_entropy(logits, jnp.maximum(labels, 0),
                             reduction="none")
        mask = (labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@dataclass
class MBartConfig(BartConfig):
    """mBART-50 shape: pre-LN layers, final LNs, scaled embeddings
    (ref: PaddleNLP ``mbart``)."""
    vocab_size: int = 250054
    scale_embedding: bool = True
    normalize_before: bool = True
    add_final_layer_norm: bool = True

    @staticmethod
    def tiny(**kw):
        return MBartConfig(**{**dict(vocab_size=128, d_model=32,
                                     encoder_layers=2, decoder_layers=2,
                                     encoder_attention_heads=4,
                                     decoder_attention_heads=4,
                                     encoder_ffn_dim=64,
                                     decoder_ffn_dim=64,
                                     max_position_embeddings=64), **kw})


class MBartForConditionalGeneration(BartForConditionalGeneration):
    pass


@dataclass
class PegasusConfig(BartConfig):
    """Pegasus shape (ref: PaddleNLP ``pegasus``): pre-LN layers, final
    LNs, STATIC sinusoidal positions at offset 0, sqrt(d)-scaled
    embeddings, NO embedding LayerNorm."""
    vocab_size: int = 96103
    scale_embedding: bool = True
    normalize_before: bool = True
    add_final_layer_norm: bool = True
    position_offset: int = 0
    add_embedding_norm: bool = False

    @staticmethod
    def tiny(**kw):
        return PegasusConfig(**{**dict(vocab_size=128, d_model=32,
                                       encoder_layers=2, decoder_layers=2,
                                       encoder_attention_heads=4,
                                       decoder_attention_heads=4,
                                       encoder_ffn_dim=64,
                                       decoder_ffn_dim=64,
                                       max_position_embeddings=64), **kw})


class PegasusForConditionalGeneration(BartForConditionalGeneration):
    pass


@dataclass
class BlenderbotConfig(BartConfig):
    """Blenderbot shape (ref: PaddleNLP ``blenderbot``): pre-LN layers,
    final LNs, learned positions at offset 0, no embedding LN — the
    Pegasus flag set with a learned (not sinusoidal) position table."""
    vocab_size: int = 8008
    normalize_before: bool = True
    add_final_layer_norm: bool = True
    position_offset: int = 0
    add_embedding_norm: bool = False

    @staticmethod
    def tiny(**kw):
        return BlenderbotConfig(**{**dict(vocab_size=128, d_model=32,
                                          encoder_layers=2,
                                          decoder_layers=2,
                                          encoder_attention_heads=4,
                                          decoder_attention_heads=4,
                                          encoder_ffn_dim=64,
                                          decoder_ffn_dim=64,
                                          max_position_embeddings=64),
                                   **kw})


class BlenderbotForConditionalGeneration(BartForConditionalGeneration):
    pass


@dataclass
class BlenderbotSmallConfig(BartConfig):
    """Blenderbot-small (90M) shape: plain BART post-LN blocks with
    offset-0 learned positions; the decoder norms embeds BEFORE adding
    positions (HF quirk, reproduced)."""
    vocab_size: int = 54944
    position_offset: int = 0
    decoder_norm_before_pos: bool = True

    @staticmethod
    def tiny(**kw):
        return BlenderbotSmallConfig(**{**dict(vocab_size=128, d_model=32,
                                               encoder_layers=2,
                                               decoder_layers=2,
                                               encoder_attention_heads=4,
                                               decoder_attention_heads=4,
                                               encoder_ffn_dim=64,
                                               decoder_ffn_dim=64,
                                               max_position_embeddings=64),
                                        **kw})


class BlenderbotSmallForConditionalGeneration(BartForConditionalGeneration):
    pass
