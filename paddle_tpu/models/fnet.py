"""FNet (ref: PaddleNLP ``paddlenlp/transformers/fnet``).

The attention-free encoder: token mixing is a 2-D Fourier transform
(real part of an FFT over sequence and hidden axes) — no attention
weights at all — followed by the usual post-LN feed-forward. A natural
fit for TPU (the FFT is one fused XLA op).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear


@dataclass
class FNetConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    intermediate_size: int = 3072
    type_vocab_size: int = 4
    max_position_embeddings: int = 512
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return FNetConfig(**{**dict(vocab_size=128, hidden_size=32,
                                    num_hidden_layers=2,
                                    intermediate_size=64,
                                    max_position_embeddings=64), **kw})


class FNetLayer(Module):
    def __init__(self, cfg: FNetConfig):
        super().__init__()
        h = cfg.hidden_size
        self.fourier_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                      dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)

    def __call__(self, x):
        four = jnp.fft.fftn(x.astype(jnp.complex64), axes=(1, 2)).real
        x = self.fourier_norm(x + four.astype(x.dtype))
        m = self.output(jax.nn.gelu(self.intermediate(x), approximate=True))
        return self.out_norm(x + m)


class FNetModel(Module):
    def __init__(self, cfg: FNetConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.projection = Linear(h, h, dtype=cfg.dtype)
        self.layers = [FNetLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]

    def __call__(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(s)[None, :])
             + self.token_type_embeddings(token_type_ids))
        x = self.projection(self.emb_norm(x))
        for lyr in self.layers:
            x = lyr(x)
        return x


class FNetForMaskedLM(Module):
    def __init__(self, cfg: FNetConfig):
        super().__init__()
        self.cfg = cfg
        self.fnet = FNetModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None):
        seq = self.fnet(input_ids, token_type_ids)
        h = self.mlm_norm(jax.nn.gelu(self.mlm_transform(seq),
                                      approximate=True))
        return h @ self.fnet.word_embeddings.weight.T + self.mlm_bias
