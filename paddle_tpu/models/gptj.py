"""GPT-J decoder LM (ref capability: PaddleNLP ``gptj`` model family /
``paddlenlp.transformers.GPTJForCausalLM``).

The INTERLEAVED-rotary member of the model zoo: rope pairs are the even/
odd lanes ``(x[2i], x[2i+1])`` over the first ``rotary_dim`` dims (unlike
LLaMA/NeoX's half-split), attention and MLP read the SAME LayerNorm
output and sum into one residual (single-LN parallel block), q/k/v/out
projections carry no bias, and the LM head is a separate biased linear
(untied).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class GPTJConfig:
    vocab_size: int = 50400
    n_embd: int = 4096
    n_layer: int = 28
    n_head: int = 16
    rotary_dim: int = 64
    n_inner: int = None                  # default 4 * n_embd
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()
        if self.n_inner is None:
            self.n_inner = 4 * self.n_embd

    @staticmethod
    def tiny(**kw):
        return GPTJConfig(**{**dict(vocab_size=128, n_embd=32, n_layer=2,
                                    n_head=4, rotary_dim=4,
                                    dtype=jnp.float32, remat=False), **kw})


class GPTJBlock(Module):
    def __init__(self, cfg: GPTJConfig):
        super().__init__()
        h = cfg.n_embd
        init = I.Normal(0.0, cfg.initializer_range)
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_epsilon,
                              dtype=cfg.dtype)
        self.q_proj = init((h, h), cfg.dtype)    # no biases (GPT-J)
        self.k_proj = init((h, h), cfg.dtype)
        self.v_proj = init((h, h), cfg.dtype)
        self.out_proj = init((h, h), cfg.dtype)
        self.fc_in = init((h, cfg.n_inner), cfg.dtype)
        self.fc_in_bias = jnp.zeros((cfg.n_inner,), cfg.dtype)
        self.fc_out = init((cfg.n_inner, h), cfg.dtype)
        self.fc_out_bias = jnp.zeros((h,), cfg.dtype)
        self.n_head = cfg.n_head
        self.rotary_dim = cfg.rotary_dim

    def __call__(self, x, cos, sin):
        b, s, hd = x.shape
        nh = self.n_head
        d = hd // nh
        rot = self.rotary_dim
        h = self.ln_1(x)                         # ONE LN feeds attn AND mlp

        def rope(t):
            r = A.apply_rope_interleaved(t[..., :rot], cos, sin)
            return jnp.concatenate([r, t[..., rot:]], axis=-1)

        q = rope((h @ self.q_proj).reshape(b, s, nh, d))
        k = rope((h @ self.k_proj).reshape(b, s, nh, d))
        v = (h @ self.v_proj).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, is_causal=True)
        att = att.reshape(b, s, hd) @ self.out_proj
        m = jax.nn.gelu(h @ self.fc_in + self.fc_in_bias, approximate=True)
        return x + att + (m @ self.fc_out + self.fc_out_bias)


class GPTJForCausalLM(Module):
    def __init__(self, cfg: GPTJConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.wte = init((cfg.vocab_size, cfg.n_embd), cfg.dtype)
        self.h = [GPTJBlock(cfg) for _ in range(cfg.n_layer)]
        self.ln_f = LayerNorm(cfg.n_embd, epsilon=cfg.layer_norm_epsilon,
                              dtype=cfg.dtype)
        self.lm_head = init((cfg.n_embd, cfg.vocab_size), cfg.dtype)
        self.lm_head_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        cos, sin = A.rope_cos_sin(s, cfg.rotary_dim)
        x = jnp.take(self.wte, input_ids, axis=0)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin)))
        for lyr in self.h:
            x = blk(lyr, x)
        x = self.ln_f(x)
        return x @ self.lm_head + self.lm_head_bias

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)


@dataclass
class CodeGenConfig(GPTJConfig):
    """CodeGen (ref: PaddleNLP ``codegen`` family) — the GPT-J block with
    a TPU-core-grouped fused QKV in the checkpoint (mp_num=4 groups,
    split order q,v,k), unpacked to separate projections at load."""
    vocab_size: int = 50400

    @staticmethod
    def tiny(**kw):
        return CodeGenConfig(**{**dict(vocab_size=128, n_embd=32,
                                       n_layer=2, n_head=4, rotary_dim=4,
                                       dtype=jnp.float32, remat=False),
                                **kw})


class CodeGenForCausalLM(GPTJForCausalLM):
    pass
