"""Mistral-7B family (ref capability: PaddleNLP
``paddlenlp/transformers/mistral/modeling.py``).

Architecturally LLaMA + causal sliding-window attention (window 4096,
GQA with 8 KV heads, theta 1e6 for v0.2+). The decoder stack is shared
with :mod:`paddle_tpu.models.llama`; the window is enforced inside the
Pallas flash kernel (band tiles only — O(S·window) not O(S²)) with an
identical-banding XLA fallback.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    num_flops_per_token,
)


class MistralConfig(LlamaConfig):
    @staticmethod
    def mistral_7b(**kw):
        return MistralConfig(**{**dict(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=32768,
            rope_theta=1e6, sliding_window=4096), **kw})

    @staticmethod
    def tiny(**kw):
        return MistralConfig(**{**dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            sliding_window=16, dtype=jnp.float32, remat=False), **kw})


class MistralModel(LlamaModel):
    pass


class MistralForCausalLM(LlamaForCausalLM):
    pass
