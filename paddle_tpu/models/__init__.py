from paddle_tpu.models.bert import (
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
)
from paddle_tpu.models.albert import AlbertConfig, AlbertForMaskedLM
from paddle_tpu.models.bart import (BartConfig,
                                    BartForConditionalGeneration,
                                    MBartConfig,
                                    MBartForConditionalGeneration)
from paddle_tpu.models.big_bird import (BigBirdConfig, BigBirdForMaskedLM,
                                        BigBirdModel)
from paddle_tpu.models.bloom import BloomConfig, BloomForCausalLM
from paddle_tpu.models.clip import (CLIPConfig, CLIPModel, CLIPTextModel,
                                    CLIPVisionModel)
from paddle_tpu.models.deberta import (DebertaV2Config,
                                       DebertaV2ForMaskedLM, DebertaV2Model)
from paddle_tpu.models.distilbert import (DistilBertConfig,
                                          DistilBertForMaskedLM,
                                          DistilBertModel)
from paddle_tpu.models.electra import (ElectraConfig, ElectraForPreTraining,
                                       ElectraModel)
from paddle_tpu.models.bart import (PegasusConfig,
                                    PegasusForConditionalGeneration)
from paddle_tpu.models.ernie import (ErnieConfig, ErnieForMaskedLM,
                                     ErnieForSequenceClassification,
                                     ErnieModel)
from paddle_tpu.models.bart import (BlenderbotConfig,
                                    BlenderbotForConditionalGeneration)
from paddle_tpu.models.ernie_m import (ErnieMConfig,
                                       ErnieMForSequenceClassification,
                                       ErnieMModel)
from paddle_tpu.models.fnet import FNetConfig, FNetForMaskedLM, FNetModel
from paddle_tpu.models.roformer import (RoFormerConfig,
                                        RoFormerForMaskedLM, RoFormerModel)
from paddle_tpu.models.roberta import (RobertaConfig, RobertaForMaskedLM,
                                       RobertaForSequenceClassification,
                                       RobertaModel)
from paddle_tpu.models.falcon import FalconConfig, FalconForCausalLM
from paddle_tpu.models.gemma import GemmaConfig, GemmaForCausalLM
from paddle_tpu.models.gpt_neox import GPTNeoXConfig, GPTNeoXForCausalLM
from paddle_tpu.models.glm import GlmConfig, GlmForCausalLM
from paddle_tpu.models.gptj import (CodeGenConfig, CodeGenForCausalLM,
                                    GPTJConfig, GPTJForCausalLM)
from paddle_tpu.models.layoutlm import (LayoutLMConfig,
                                        LayoutLMForMaskedLM, LayoutLMModel)
from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
from paddle_tpu.models.megatron_bert import (MegatronBertConfig,
                                             MegatronBertForMaskedLM,
                                             MegatronBertModel)
from paddle_tpu.models.mpnet import (MPNetConfig, MPNetForMaskedLM,
                                     MPNetModel)
from paddle_tpu.models.nezha import (NezhaConfig, NezhaForMaskedLM,
                                     NezhaModel)
from paddle_tpu.models.phi import PhiConfig, PhiForCausalLM
from paddle_tpu.models.qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM
from paddle_tpu.models.whisper import (WhisperConfig,
                                       WhisperForConditionalGeneration)
from paddle_tpu.models.xlnet import (XLNetConfig, XLNetLMHeadModel,
                                     XLNetModel)
from paddle_tpu.models.opt import OPTConfig, OPTForCausalLM
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, LlamaModel
from paddle_tpu.models.moe_llm import MoEConfig, MoEForCausalLM
from paddle_tpu.models.resnet import (
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
from paddle_tpu.models.conformer import (ConformerConfig, ConformerEncoder,
                                         ConformerForCTC)
from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM, MistralModel
from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM, Qwen2Model
from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration
from paddle_tpu.models import convert
