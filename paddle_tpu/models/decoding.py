"""Autoregressive generation with a static KV cache (ref capability:
``fused_multi_transformer`` inference kernels + PaddleNLP ``generate()``).

TPU-first: the decode loop is a ``lax.while_loop`` over a PRE-ALLOCATED
[B, max_len, H, D] cache — static shapes, one compiled program for the whole
generation, cache updated via dynamic_update_slice (no recompiles per step,
unlike naive eager decoding). Prefill and decode are the same jitted fn.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops import attention as A


@dataclass
class KVCache:
    """Per-layer [B, max_len, H_kv, D] k/v buffers + current length."""
    k: list
    v: list
    length: jnp.ndarray  # scalar int32

    @staticmethod
    def init(num_layers, batch, max_len, num_kv_heads, head_dim, dtype):
        z = lambda: jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype)
        return KVCache([z() for _ in range(num_layers)],
                       [z() for _ in range(num_layers)],
                       jnp.zeros((), jnp.int32))


jax.tree_util.register_pytree_node(
    KVCache,
    lambda c: ((c.k, c.v, c.length), None),
    lambda aux, ch: KVCache(*ch))


def _attend_with_cache(q, k_cache, v_cache, cur_len, new_k, new_v, pos):
    """Write new_k/new_v at pos, attend q over cache[:pos+new]."""
    k_cache = lax.dynamic_update_slice_in_dim(k_cache, new_k, pos, axis=1)
    v_cache = lax.dynamic_update_slice_in_dim(v_cache, new_v, pos, axis=1)
    sq = q.shape[1]
    total = pos + sq
    # mask: key index must be <= query absolute position
    key_idx = jnp.arange(k_cache.shape[1])[None, :]
    q_idx = pos + jnp.arange(sq)[:, None]
    mask = (key_idx <= q_idx)[None, None]  # [1,1,Sq,Smax]
    out = A.xla_attention(q, k_cache, v_cache, attn_mask=mask)
    return out, k_cache, v_cache


def llama_forward_with_cache(model, input_ids, cache: KVCache, pos):
    """One forward over `input_ids` (prefill chunk or single token)."""
    cfg = model.cfg
    x = jnp.take(model.model.embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    positions = pos + jnp.arange(input_ids.shape[1])
    cos, sin = A.rope_cos_sin(input_ids.shape[1], d, base=cfg.rope_theta,
                              position_ids=positions)
    new_k_list, new_v_list = [], []
    for li, lyr in enumerate(model.model.layers):
        h = lyr.input_layernorm(x)
        b, s, _ = h.shape
        att = lyr.self_attn
        qkv = h @ att.qkv_proj
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv, hd), cos, sin)
        v = v.reshape(b, s, nkv, hd)
        out, k_c, v_c = _attend_with_cache(q, cache.k[li], cache.v[li],
                                           cache.length, k, v, pos)
        new_k_list.append(k_c)
        new_v_list.append(v_c)
        x = x + out.reshape(b, s, nh * hd) @ att.o_proj
        x = x + lyr.mlp(lyr.post_attention_layernorm(x))
    x = model.model.norm(x)
    logits = model.logits(x)
    new_cache = KVCache(new_k_list, new_v_list, pos + input_ids.shape[1])
    return logits, new_cache


def _sample(logits, rng, temperature, top_k, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(model, input_ids, max_new_tokens=32, temperature=0.0, top_k=None,
             top_p=None, eos_token_id=None, rng=None):
    """Greedy/temperature/top-k/top-p decoding (ref PaddleNLP GenerationMixin).

    One jitted while_loop; returns [B, prompt+max_new_tokens].
    """
    cfg = model.cfg
    b, prompt_len = input_ids.shape
    max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    cache = KVCache.init(cfg.num_hidden_layers, b, max_len,
                         cfg.num_key_value_heads,
                         cfg.hidden_size // cfg.num_attention_heads, cfg.dtype)

    @jax.jit
    def run(model, input_ids, cache, rng):
        logits, cache = llama_forward_with_cache(model, input_ids, cache, 0)
        next_tok = _sample(logits[:, -1], rng, temperature, top_k, top_p)
        tokens = jnp.concatenate(
            [input_ids, jnp.zeros((b, max_new_tokens), input_ids.dtype)], axis=1)
        tokens = tokens.at[:, prompt_len].set(next_tok)
        done = jnp.zeros((b,), bool) if eos_token_id is None else (next_tok == eos_token_id)

        def cond(state):
            i, tokens, cache, rng, done = state
            return jnp.logical_and(i < max_new_tokens - 1, ~jnp.all(done))

        def body(state):
            i, tokens, cache, rng, done = state
            rng, sub = jax.random.split(rng)
            cur = lax.dynamic_slice_in_dim(tokens, prompt_len + i, 1, axis=1)
            logits, cache = llama_forward_with_cache(model, cur, cache, prompt_len + i)
            nxt = _sample(logits[:, -1], sub, temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], prompt_len + i + 1, axis=1)
            return (i + 1, tokens, cache, rng, done)

        state = (jnp.zeros((), jnp.int32), tokens, cache, rng, done)
        _, tokens, _, _, _ = lax.while_loop(cond, body, state)
        return tokens

    return run(model, input_ids, cache, rng)
