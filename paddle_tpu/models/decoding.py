"""Autoregressive generation with a static KV cache (ref capability:
``fused_multi_transformer`` inference kernels + PaddleNLP ``generate()``).

TPU-first: the decode loop is a ``lax.while_loop`` over a PRE-ALLOCATED
[B, max_len, H, D] cache — static shapes, one compiled program for the whole
generation, cache updated via dynamic_update_slice (no recompiles per step,
unlike naive eager decoding). Prefill and decode are the same jitted fn.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops import attention as A
from paddle_tpu.quantization import wo_matmul


@dataclass
class KVCache:
    """Per-layer [B, cap, H_kv, D] k/v buffers + current length.

    With ``window`` set (sliding-window models), the cache is a RING of
    ``cap = min(max_len, window)`` slots: writes land at ``pos % cap`` and
    ``slot_pos`` tracks each slot's absolute position for masking — decode
    memory is bounded by the window, not the generation length."""
    k: list
    v: list
    length: jnp.ndarray  # scalar int32
    slot_pos: object = None  # [cap] int32 absolute positions, or None

    @staticmethod
    def init(num_layers, batch, max_len, num_kv_heads, head_dim, dtype,
             window=None):
        cap = max_len if window is None else min(max_len, window)
        z = lambda: jnp.zeros((batch, cap, num_kv_heads, head_dim), dtype)
        slot_pos = None if window is None else jnp.full((cap,), -1, jnp.int32)
        return KVCache([z() for _ in range(num_layers)],
                       [z() for _ in range(num_layers)],
                       jnp.zeros((), jnp.int32), slot_pos)


jax.tree_util.register_pytree_node(
    KVCache,
    lambda c: ((c.k, c.v, c.length, c.slot_pos), None),
    lambda aux, ch: KVCache(*ch))


def _attend_with_cache(q, k_cache, v_cache, new_k, new_v, pos,
                       window=None, slot_pos=None):
    """Write new_k/new_v at pos, attend q over the cache. ``window`` keeps
    decode consistent with sliding-window training (Mistral). With
    ``slot_pos`` the cache is a ring of ``cap`` slots: writes wrap at
    ``pos % cap`` and masking uses each slot's absolute position."""
    sq = q.shape[1]
    cap = k_cache.shape[1]
    q_idx = pos + jnp.arange(sq)[:, None]
    if slot_pos is not None:
        if sq > 1:
            # prefill: the whole chunk is in hand — attend over it directly
            # (the ring may be smaller than the chunk, so early queries'
            # keys would already be evicted); then keep only the last cap
            # positions in the ring for decode.
            # The chunk-local attention below IGNORES pre-existing ring
            # contents, so resuming/chunked prefill over a non-empty ring
            # would be silently wrong — require a statically-known pos==0
            # (generate()/beam_search prefill with a literal 0).
            if not (isinstance(pos, int) and pos == 0):
                raise NotImplementedError(
                    "ring-cache (windowed) prefill requires static pos==0; "
                    f"got pos={pos!r}. Chunked prefill over an existing "
                    "ring cache is not supported — prefill the whole "
                    "prompt at once.")
            a = jnp.arange(sq)
            keep = a[:, None] >= a[None, :]
            if window is not None:
                keep &= (a[:, None] - a[None, :]) < window
            out = A.xla_attention(q, new_k, new_v, attn_mask=keep[None, None])
            tail = min(sq, cap)
            tail_pos = pos + jnp.arange(sq - tail, sq)
            idx = tail_pos % cap
            k_cache = k_cache.at[:, idx].set(new_k[:, sq - tail:])
            v_cache = v_cache.at[:, idx].set(new_v[:, sq - tail:])
            return out, k_cache, v_cache
        idx = (pos + jnp.arange(sq)) % cap
        k_cache = k_cache.at[:, idx].set(new_k)
        v_cache = v_cache.at[:, idx].set(new_v)
        key_abs = slot_pos[None, :]  # [1, cap] (already updated by caller)
        keep = (key_abs >= 0) & (key_abs <= q_idx)
        if window is not None:
            keep &= (q_idx - key_abs) < window
    else:
        k_cache = lax.dynamic_update_slice_in_dim(k_cache, new_k, pos, axis=1)
        v_cache = lax.dynamic_update_slice_in_dim(v_cache, new_v, pos, axis=1)
        # mask: key index must be <= query absolute position (and in-window)
        key_idx = jnp.arange(cap)[None, :]
        keep = key_idx <= q_idx
        if window is not None:
            keep &= (q_idx - key_idx) < window
    mask = keep[None, None]  # [1,1,Sq,cap]
    out = A.xla_attention(q, k_cache, v_cache, attn_mask=mask)
    return out, k_cache, v_cache


def llama_forward_with_cache(model, input_ids, cache: KVCache, pos):
    """One forward over `input_ids` (prefill chunk or single token)."""
    cfg = model.cfg
    x = jnp.take(model.model.embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    positions = pos + jnp.arange(input_ids.shape[1])
    # rope scaling: linear/ntk are static; dynamic-NTK rides the TRACED
    # current length (pos + chunk), matching HF generation semantics
    # (earlier cache entries keep the base they were rotated with)
    cos, sin = A.rope_cos_sin(input_ids.shape[1], d, base=cfg.rope_theta,
                              position_ids=positions,
                              scaling=getattr(cfg, "rope_scaling", None),
                              max_position_embeddings=getattr(
                                  cfg, "max_position_embeddings", None),
                              cur_len=pos + input_ids.shape[1],
                              allow_dynamic=False)
    slot_pos = cache.slot_pos
    if slot_pos is not None:  # ring cache: record absolute slot positions
        cap = slot_pos.shape[0]
        s = input_ids.shape[1]
        tail = min(s, cap)  # prefill writes only the last cap positions
        tail_pos = positions[s - tail:]
        slot_pos = slot_pos.at[tail_pos % cap].set(tail_pos)
    new_k_list, new_v_list = [], []
    for li, lyr in enumerate(model.model.layers):
        h = lyr.input_layernorm(x)
        b, s, _ = h.shape
        att = lyr.self_attn
        qkv = wo_matmul(h, att.qkv_proj)
        if getattr(att, "qkv_bias", None) is not None:  # Qwen2
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv, hd), cos, sin)
        v = v.reshape(b, s, nkv, hd)
        out, k_c, v_c = _attend_with_cache(q, cache.k[li], cache.v[li],
                                           k, v, pos,
                                           window=getattr(cfg, "sliding_window",
                                                          None),
                                           slot_pos=slot_pos)
        new_k_list.append(k_c)
        new_v_list.append(v_c)
        x = x + wo_matmul(out.reshape(b, s, nh * hd), att.o_proj)
        x = x + lyr.mlp(lyr.post_attention_layernorm(x))
    x = model.model.norm(x)
    logits = model.logits(x)
    new_cache = KVCache(new_k_list, new_v_list, pos + input_ids.shape[1],
                        slot_pos)
    return logits, new_cache


def _apply_repetition_penalty(logits, appeared, penalty):
    """CTRL-style penalty (ref PaddleNLP GenerationMixin): divide positive
    scores / multiply negative scores of already-generated tokens."""
    if penalty == 1.0:
        return logits
    penalised = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(appeared, penalised, logits)


def _sample(logits, rng, temperature, top_k, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def _sample_rows(logits, rng, temps, top_ps, top_k=None, bias=None):
    """Per-ROW temperature/top-p sampling (the serving engine's
    per-request params; ref PaddleNLP predictor per-request
    GenerationConfig). ``temps``/``top_ps``: [B] traced — temperature 0
    means greedy FOR THAT ROW; top_p 1.0 disables the nucleus cut.
    ``top_k`` stays global/static. ``bias`` ([B, V] additive, 0 / -1e30)
    is the grammar-constraint mask (ISSUE 14): added BEFORE the
    temperature scale and the greedy argmax, so both the stochastic and
    the greedy row paths can only pick mask-legal tokens."""
    if bias is not None:
        logits = logits + bias
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
    scaled = logits / safe_t
    if top_k is not None and top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_ps[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temps > 0, sampled, jnp.argmax(logits, axis=-1))


def generate(model, input_ids, max_new_tokens=32, temperature=0.0, top_k=None,
             top_p=None, eos_token_id=None, rng=None, repetition_penalty=1.0,
             min_new_tokens=0):
    """Greedy/temperature/top-k/top-p decoding (ref PaddleNLP GenerationMixin)
    with repetition penalty and min-length constraint.

    One jitted while_loop; returns [B, prompt+max_new_tokens].
    """
    cfg = model.cfg
    b, prompt_len = input_ids.shape
    max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    cache = KVCache.init(cfg.num_hidden_layers, b, max_len,
                         cfg.num_key_value_heads,
                         cfg.hidden_size // cfg.num_attention_heads, cfg.dtype,
                         window=getattr(cfg, "sliding_window", None))

    def constrain(logits, appeared, gen_len):
        logits = _apply_repetition_penalty(logits, appeared, repetition_penalty)
        if eos_token_id is not None and min_new_tokens > 0:
            logits = jnp.where(
                (gen_len < min_new_tokens)
                & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
                -1e30, logits)
        return logits

    @jax.jit
    def run(model, input_ids, cache, rng):
        vocab = cfg.vocab_size
        appeared = jnp.zeros((b, vocab), bool)
        appeared = appeared.at[jnp.arange(b)[:, None], input_ids].set(True)
        logits, cache = llama_forward_with_cache(model, input_ids, cache, 0)
        logits = constrain(logits[:, -1].astype(jnp.float32), appeared, 0)
        next_tok = _sample(logits, rng, temperature, top_k, top_p)
        appeared = appeared.at[jnp.arange(b), next_tok].set(True)
        tokens = jnp.concatenate(
            [input_ids, jnp.zeros((b, max_new_tokens), input_ids.dtype)], axis=1)
        tokens = tokens.at[:, prompt_len].set(next_tok)
        done = jnp.zeros((b,), bool) if eos_token_id is None else (next_tok == eos_token_id)

        def cond(state):
            i, tokens, cache, rng, done, appeared = state
            return jnp.logical_and(i < max_new_tokens - 1, ~jnp.all(done))

        def body(state):
            i, tokens, cache, rng, done, appeared = state
            rng, sub = jax.random.split(rng)
            cur = lax.dynamic_slice_in_dim(tokens, prompt_len + i, 1, axis=1)
            logits, cache = llama_forward_with_cache(model, cur, cache, prompt_len + i)
            logits = constrain(logits[:, -1].astype(jnp.float32), appeared, i + 1)
            nxt = _sample(logits, sub, temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            appeared = appeared.at[jnp.arange(b), nxt].set(True)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], prompt_len + i + 1, axis=1)
            return (i + 1, tokens, cache, rng, done, appeared)

        state = (jnp.zeros((), jnp.int32), tokens, cache, rng, done, appeared)
        _, tokens, _, _, _, _ = lax.while_loop(cond, body, state)
        return tokens

    return run(model, input_ids, cache, rng)


def beam_select(running_lp, seqs, fin_seqs, fin_scores, logp, i,
                prompt_len, eos_token_id, length_penalty):
    """One beam expansion: place token i, split 2K candidates into
    finished (eos) and running pools. Shapes: running_lp/fin_scores
    [B, K], seqs/fin_seqs [B, K, L], logp [B, K, V]. Shared by the
    static-cache beam_search AND the paged beam (models/paged.py) so
    their selection math can never drift apart."""
    b, K = running_lp.shape
    V = logp.shape[-1]
    NEG = jnp.float32(-1e9)
    total = running_lp[:, :, None] + logp  # [B, K, V]
    cand_lp, cand_idx = lax.top_k(total.reshape(b, K * V), 2 * K)
    beam = cand_idx // V  # [B, 2K]
    tok = cand_idx % V
    cand_seqs = jnp.take_along_axis(seqs, beam[:, :, None], axis=1)
    cand_seqs = cand_seqs.at[:, :, prompt_len + i].set(tok)

    if eos_token_id is not None:
        is_eos = tok == eos_token_id
    else:
        is_eos = jnp.zeros_like(tok, bool)
    # finished pool: merge newly-finished candidates, keep top K
    cand_score = cand_lp / ((i + 1.0) ** length_penalty)
    all_scores = jnp.concatenate(
        [fin_scores, jnp.where(is_eos, cand_score, NEG)], axis=1)
    all_seqs = jnp.concatenate([fin_seqs, cand_seqs], axis=1)
    fin_scores, fin_idx = lax.top_k(all_scores, K)
    fin_seqs = jnp.take_along_axis(all_seqs, fin_idx[:, :, None], axis=1)

    # running pool: best K non-eos candidates
    run_lp_cand = jnp.where(is_eos, NEG, cand_lp)
    running_lp, run_idx = lax.top_k(run_lp_cand, K)
    seqs = jnp.take_along_axis(cand_seqs, run_idx[:, :, None], axis=1)
    new_beam = jnp.take_along_axis(beam, run_idx, axis=1)  # [B, K]
    new_tok = jnp.take_along_axis(tok, run_idx, axis=1)
    return running_lp, seqs, fin_seqs, fin_scores, new_beam, new_tok


def beam_search(model, input_ids, max_new_tokens=32, num_beams=4,
                length_penalty=1.0, eos_token_id=None):
    """Beam search with a beam-gathered KV cache (ref: PaddleNLP
    ``GenerationMixin.beam_search`` / ``BeamSearchScorer``).

    TPU-native: beams live in a [B*K] leading dim so every step is one
    batched forward; beam reordering is a gather on the cache pytree inside
    ``lax.scan`` — static shapes, single compile.

    Returns (sequences [B, prompt+max_new], scores [B]) — the best finished
    hypothesis per batch (length-penalised log prob, PaddleNLP convention
    ``sum logp / len**alpha``).
    """
    cfg = model.cfg
    b, prompt_len = input_ids.shape
    K, V = num_beams, cfg.vocab_size
    max_len = prompt_len + max_new_tokens
    NEG = jnp.float32(-1e9)

    cache = KVCache.init(cfg.num_hidden_layers, b, max_len,
                         cfg.num_key_value_heads,
                         cfg.hidden_size // cfg.num_attention_heads, cfg.dtype)

    def gather_beams(tree, beam_idx):
        """tree leaves [B*K, ...] reordered by beam_idx [B, K] (scalar leaves
        like the cache length pass through)."""
        def g(x):
            if jnp.ndim(x) == 0:
                return x
            xk = x.reshape((b, K) + x.shape[1:])
            idx = beam_idx.reshape((b, K) + (1,) * (x.ndim - 1))
            return jnp.take_along_axis(xk, idx, axis=1).reshape(x.shape)
        return jax.tree_util.tree_map(g, tree)

    @jax.jit
    def run(model, input_ids, cache):
        # prefill ONCE at batch B (beams are byte-identical pre-fork), then
        # tile the cache along a beam axis
        logits, cache = llama_forward_with_cache(model, input_ids, cache, 0)
        cache = jax.tree_util.tree_map(
            lambda x: x if jnp.ndim(x) == 0 else jnp.repeat(x, K, axis=0), cache)
        logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
        logp = jnp.broadcast_to(logp[:, None, :], (b, K, V))

        # beam 0 starts live, the rest masked so step 0 picks K distinct tokens
        running_lp = jnp.tile(jnp.array([0.0] + [NEG] * (K - 1)), (b, 1))
        seqs = jnp.zeros((b, K, max_len), input_ids.dtype)
        seqs = seqs.at[:, :, :prompt_len].set(input_ids[:, None, :])
        fin_seqs = jnp.zeros_like(seqs)
        fin_scores = jnp.full((b, K), NEG)

        def select(running_lp, seqs, fin_seqs, fin_scores, logp, i):
            return beam_select(running_lp, seqs, fin_seqs, fin_scores,
                               logp, i, prompt_len, eos_token_id,
                               length_penalty)

        def step(carry, i):
            running_lp, seqs, fin_seqs, fin_scores, cache, logp = carry
            running_lp, seqs, fin_seqs, fin_scores, new_beam, new_tok = select(
                running_lp, seqs, fin_seqs, fin_scores, logp, i)
            cache = gather_beams(cache, new_beam)
            cur = new_tok.reshape(b * K, 1)
            logits, cache = llama_forward_with_cache(
                model, cur, cache, prompt_len + i)
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1).reshape(b, K, V)
            return (running_lp, seqs, fin_seqs, fin_scores, cache, logp), None

        carry = (running_lp, seqs, fin_seqs, fin_scores, cache, logp)
        (running_lp, seqs, fin_seqs, fin_scores, _, logp), _ = lax.scan(
            step, carry, jnp.arange(max_new_tokens - 1))
        # last token: pure selection, no forward needed after it
        running_lp, seqs, fin_seqs, fin_scores, _, _ = select(
            running_lp, seqs, fin_seqs, fin_scores, logp, max_new_tokens - 1)

        # merge still-running beams (at full length) with the finished pool
        run_score = running_lp / (float(max_new_tokens) ** length_penalty)
        all_scores = jnp.concatenate([fin_scores, run_score], axis=1)
        all_seqs = jnp.concatenate([fin_seqs, seqs], axis=1)
        best = jnp.argmax(all_scores, axis=1)
        best_seqs = jnp.take_along_axis(
            all_seqs, best[:, None, None], axis=1)[:, 0]
        best_scores = jnp.take_along_axis(all_scores, best[:, None], axis=1)[:, 0]
        if eos_token_id is not None:
            # early-finished hypotheses carry 0s after eos — pad with eos
            # (generate()'s convention)
            gen = best_seqs[:, prompt_len:]
            seen = jnp.cumsum(gen == eos_token_id, axis=1)
            after = jnp.concatenate(
                [jnp.zeros((b, 1), bool), (seen > 0)[:, :-1]], axis=1)
            best_seqs = best_seqs.at[:, prompt_len:].set(
                jnp.where(after, eos_token_id, gen))
        return best_seqs, best_scores

    return run(model, input_ids, cache)


def generic_generate(model, input_ids, max_new_tokens=32, temperature=0.0,
                     top_k=None, top_p=None, eos_token_id=None, rng=None,
                     repetition_penalty=1.0, min_new_tokens=0):
    """Family-agnostic decoding (ref PaddleNLP GenerationMixin over every
    causal architecture): works with ANY causal LM whose
    ``__call__(ids [B, S]) -> logits [B, S, V]`` — BLOOM, Falcon,
    GPT-J/NeoX, OPT, Gemma, Qwen2-MoE, custom models — with the same
    sampling/penalty/EOS semantics as ``generate``.

    The whole buffer is re-forwarded each step (no KV cache): position
    ``p``'s logits depend only on tokens ``<= p`` under causal masking,
    so the zero-padded future is inert. O(S^2) attention per token —
    the correctness-first generic path; the LLaMA family's ``generate``
    is the cached fast path. One jitted while_loop, fixed shapes.
    """
    cfg = model.cfg
    b, prompt_len = input_ids.shape
    max_len = prompt_len + max_new_tokens
    vocab = cfg.vocab_size
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def constrain(logits, appeared, gen_len):
        logits = _apply_repetition_penalty(logits, appeared,
                                           repetition_penalty)
        if eos_token_id is not None and min_new_tokens > 0:
            logits = jnp.where(
                (gen_len < min_new_tokens)
                & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
                -1e30, logits)
        return logits

    @jax.jit
    def run(model, input_ids, rng):
        tokens = jnp.concatenate(
            [input_ids, jnp.zeros((b, max_new_tokens), input_ids.dtype)],
            axis=1)
        appeared = jnp.zeros((b, vocab), bool)
        appeared = appeared.at[jnp.arange(b)[:, None], input_ids].set(True)

        def logits_at(tokens, pos):
            lg = model(tokens).astype(jnp.float32)
            return lax.dynamic_index_in_dim(lg, pos, 1, keepdims=False)

        logits = constrain(logits_at(tokens, prompt_len - 1), appeared, 0)
        next_tok = _sample(logits, rng, temperature, top_k, top_p)
        appeared = appeared.at[jnp.arange(b), next_tok].set(True)
        tokens = tokens.at[:, prompt_len].set(next_tok)
        done = (jnp.zeros((b,), bool) if eos_token_id is None
                else (next_tok == eos_token_id))

        def cond(state):
            i, tokens, rng, done, appeared = state
            return jnp.logical_and(i < max_new_tokens - 1, ~jnp.all(done))

        def body(state):
            i, tokens, rng, done, appeared = state
            rng, sub = jax.random.split(rng)
            logits = constrain(logits_at(tokens, prompt_len + i), appeared,
                               i + 1)
            nxt = _sample(logits, sub, temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            appeared = appeared.at[jnp.arange(b), nxt].set(True)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], prompt_len + i + 1, axis=1)
            return (i + 1, tokens, rng, done, appeared)

        state = (jnp.zeros((), jnp.int32), tokens, rng, done, appeared)
        state = lax.while_loop(cond, body, state)
        return state[1]

    return run(model, jnp.asarray(input_ids), rng)


def generic_seq2seq_generate(model, encoder_inputs, max_new_tokens=20,
                             decoder_start_token_id=0, eos_token_id=None,
                             attention_mask=None, temperature=0.0,
                             top_k=None, top_p=None, rng=None):
    """Greedy decode for ANY encoder-decoder whose
    ``__call__(encoder_inputs, decoder_input_ids[, attention_mask])``
    returns [B, L, vocab] logits — BART/mBART/Pegasus, Whisper, custom
    (T5 ships its own encode-once ``generate``). Full re-forward per
    step (causal decoder masking makes the zero-padded future inert);
    one jitted fori_loop, fixed shapes. Returns [B, max_new_tokens]
    (EOS-filled after a row finishes)."""
    b = encoder_inputs.shape[0]
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    @jax.jit
    def run(model, encoder_inputs, attention_mask, rng):
        tokens = jnp.full((b, max_new_tokens + 1), decoder_start_token_id,
                          jnp.int32)

        def fwd(dec):
            if attention_mask is not None:
                return model(encoder_inputs, dec, attention_mask)
            return model(encoder_inputs, dec)

        def body(i, state):
            tokens, done, rng = state
            rng, sub = jax.random.split(rng)
            logits = fwd(tokens).astype(jnp.float32)
            step = lax.dynamic_index_in_dim(logits, i, 1, keepdims=False)
            nxt = _sample(step, sub, temperature, top_k,
                          top_p).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            tokens = tokens.at[:, i + 1].set(nxt)
            return tokens, done, rng

        done = jnp.zeros((b,), bool)
        tokens, _, _ = lax.fori_loop(0, max_new_tokens, body,
                                     (tokens, done, rng))
        return tokens[:, 1:]

    return run(model, jnp.asarray(encoder_inputs), attention_mask, rng)


def generic_seq2seq_beam_search(model, encoder_inputs, max_new_tokens=20,
                                num_beams=4, decoder_start_token_id=0,
                                eos_token_id=None, length_penalty=1.0,
                                attention_mask=None):
    """Beam search for ANY encoder-decoder ``__call__(enc, dec[, mask])``
    family — the same ``beam_select`` math as the causal-LM and paged
    beams, over full decoder re-forwards (beams ride a [B*K] leading dim;
    one batched forward per step). Returns
    (sequences [B, max_new_tokens], scores [B])."""
    enc = jnp.asarray(encoder_inputs)
    b = enc.shape[0]
    K = num_beams
    L = max_new_tokens + 1
    enc_t = jnp.repeat(enc, K, axis=0)
    mask_t = (None if attention_mask is None
              else jnp.repeat(jnp.asarray(attention_mask), K, axis=0))

    @jax.jit
    def run(model, enc_t, mask_t):
        NEG = jnp.float32(-1e9)
        seqs = jnp.full((b, K, L), decoder_start_token_id, jnp.int32)
        running_lp = jnp.broadcast_to(
            jnp.asarray([0.0] + [NEG] * (K - 1)), (b, K)).astype(jnp.float32)
        fin_seqs = jnp.zeros_like(seqs)
        fin_scores = jnp.full((b, K), NEG)

        def fwd(dec):
            if mask_t is not None:
                return model(enc_t, dec, mask_t)
            return model(enc_t, dec)

        def body(i, state):
            running_lp, seqs, fin_seqs, fin_scores = state
            logits = fwd(seqs.reshape(b * K, L)).astype(jnp.float32)
            step = lax.dynamic_index_in_dim(logits, i, 1, keepdims=False)
            logp = jax.nn.log_softmax(step, axis=-1).reshape(b, K, -1)
            running_lp, seqs, fin_seqs, fin_scores, _, _ = beam_select(
                running_lp, seqs, fin_seqs, fin_scores, logp, i, 1,
                eos_token_id, length_penalty)
            return running_lp, seqs, fin_seqs, fin_scores

        state = (running_lp, seqs, fin_seqs, fin_scores)
        running_lp, seqs, fin_seqs, fin_scores = lax.fori_loop(
            0, max_new_tokens, body, state)

        run_score = running_lp / (float(max_new_tokens) ** length_penalty)
        all_scores = jnp.concatenate([fin_scores, run_score], axis=1)
        all_seqs = jnp.concatenate([fin_seqs, seqs], axis=1)
        best = jnp.argmax(all_scores, axis=1)
        best_seq = jnp.take_along_axis(all_seqs, best[:, None, None],
                                       axis=1)[:, 0]
        best_score = jnp.take_along_axis(all_scores, best[:, None],
                                         axis=1)[:, 0]
        gen = best_seq[:, 1:]
        if eos_token_id is not None:
            seen = jnp.cumsum(gen == eos_token_id, axis=1)
            after = jnp.concatenate(
                [jnp.zeros((b, 1), bool), (seen > 0)[:, :-1]], axis=1)
            gen = jnp.where(after, eos_token_id, gen)
        return gen, best_score

    return run(model, enc_t, mask_t)
