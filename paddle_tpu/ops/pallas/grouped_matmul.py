"""Grouped (ragged) matmul for MoE expert computation — the kernel behind
dropless mixture-of-experts (ref: Paddle's ``incubate/nn/functional/moe``
surface — ``moe_dispatch`` / ``moe_ffn`` / ``moe_combine`` — whose FFN leg
this replaces; MegaBlocks, Gale et al. 2023, for the dropless formulation).

``grouped_matmul(lhs, rhs, group_sizes)`` computes, for rows of ``lhs``
sorted so that each expert's tokens are contiguous,

    out[r] = lhs[r] @ rhs[g(r)]        g(r) = the group (expert) owning row r,

i.e. one matmul per expert over a ragged row partition described by
``group_sizes`` — without the ``(tokens, experts, capacity)`` one-hot
dispatch the dense GShard path pays for. Capacity padding disappears:
FLOPs track ``sum(group_sizes)`` (= tokens x top-k), not
``experts x capacity``.

Layout strategy (TPU kernel): each expert's row segment is padded up to a
multiple of ``block_m`` so every row tile belongs to exactly ONE expert.
The padded row count is bounded statically by ``m + experts*block_m``, so
shapes stay static while the *live* tile count is a traced scalar. The
grid is (col-tile, row-tile) with the row dimension innermost; two scalar-
prefetch arrays (``tile->expert`` id map and the live-tile count) steer the
BlockSpec index maps:

  * empty experts own zero tiles — their weights are never fetched and no
    grid step touches them (the "skip empty tiles" property);
  * consecutive tiles of the same expert map to the same ``rhs`` block, so
    Mosaic's revisit rule fetches each expert's weights once per column
    tile (the "read weights once per tile" property);
  * trailing dead grid steps clamp every index map to the last live tile —
    a consecutive revisit of an already-final output block, which Mosaic
    neither recomputes nor re-flushes (`pl.when` skips the body).

Backward is two more grouped products (``custom_vjp``): ``dlhs`` reuses the
forward kernel against ``rhs`` transposed; ``drhs`` runs a second kernel
with the row dimension innermost under (k-tile, n-tile) so per-expert
partial products accumulate in the revisited output block.

Three implementations share the API:
  * ``impl="pallas"``  — the TPU kernel above (``interpret=`` runs it on
    CPU through the Pallas interpreter for kernel-parity tests);
  * ``impl="xla"``     — same sort+segment layout lowered to one batched
    matmul over row tiles with per-tile gathered weights (the fast
    non-TPU path; measured 2.4x over dense dropless on CPU);
  * ``impl="dense"``   — the one-hot ``jnp.einsum`` reference.
``PT_GROUPED_GEMM=0`` routes every call to the dense reference (read at
trace time — re-trace after flipping, e.g. ``models.paged.clear_jit_caches``).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["grouped_matmul", "grouped_matmul_reference", "grouped_gemm_enabled"]

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_K = 512
_float0 = jax.dtypes.float0

# CompilerParams was TPUCompilerParams before the pallas API rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


def grouped_gemm_enabled() -> bool:
    """Kill switch: ``PT_GROUPED_GEMM=0`` restores the dense path."""
    return os.environ.get("PT_GROUPED_GEMM", "1") != "0"


def _fit(blk, n):
    """Largest power-of-two divisor of ``n`` that is <= ``blk``."""
    while n % blk:
        blk //= 2
    return max(blk, 1)


def grouped_matmul_reference(lhs, rhs, group_sizes):
    """Dense one-hot einsum reference: O(m*e*k*n), exact semantics."""
    m = lhs.shape[0]
    e = rhs.shape[0]
    ends = jnp.cumsum(group_sizes.astype(jnp.int32))
    gid = jnp.searchsorted(ends, jnp.arange(m, dtype=jnp.int32), side="right")
    onehot = jax.nn.one_hot(gid, e, dtype=lhs.dtype)
    return jnp.einsum("me,mk,ekn->mn", onehot, lhs, rhs)


def _plan(m, e, group_sizes, bm):
    """Static-shape tile plan over the ragged row partition.

    Returns ``(gid, total, dest, w)`` where ``w = ceil(m/bm) + e`` is the
    static tile-count bound, ``total`` (traced) is the live tile count,
    ``gid[w]`` maps each tile slot to its expert (clamped past ``total`` so
    dead grid steps revisit the last live blocks), and ``dest[r]`` is row
    r's position in the segment-aligned padded buffer of ``w*bm`` rows.
    """
    sizes = group_sizes.astype(jnp.int32)
    padded = ((sizes + bm - 1) // bm) * bm
    tile_ends = jnp.cumsum(padded // bm)
    total = tile_ends[-1]
    w = -(-m // bm) + e
    w_ids = jnp.minimum(jnp.arange(w, dtype=jnp.int32), total - 1)
    gid = jnp.searchsorted(tile_ends, w_ids, side="right").astype(jnp.int32)
    ends = jnp.cumsum(sizes)
    shift = (jnp.cumsum(padded) - padded) - (ends - sizes)
    row_gid = jnp.searchsorted(ends, jnp.arange(m, dtype=jnp.int32),
                               side="right")
    dest = jnp.arange(m, dtype=jnp.int32) + shift[jnp.minimum(row_gid, e - 1)]
    return gid, total, dest, w


# --------------------------------------------------------------------- xla
def _xla_grouped(lhs, rhs, group_sizes, bm):
    """Sort+segment layout lowered to plain XLA: scatter rows into
    expert-aligned ``bm``-row tiles, gather each tile's expert weights,
    one batched matmul. Differentiable by construction."""
    m, k = lhs.shape
    e, _, n = rhs.shape
    gid, _, dest, w = _plan(m, e, group_sizes, bm)
    xp = jnp.zeros((w * bm, k), lhs.dtype).at[dest].set(lhs)
    yt = jnp.einsum("wbk,wkn->wbn", xp.reshape(w, bm, k), rhs[gid],
                    preferred_element_type=jnp.float32)
    return yt.reshape(w * bm, n).astype(lhs.dtype)[dest]


# ------------------------------------------------------------------ pallas
def _fwd_kernel(gid_ref, tot_ref, x_ref, w_ref, o_ref):
    del gid_ref
    wi = pl.program_id(1)

    @pl.when(wi < tot_ref[0])
    def _():
        o_ref[...] = jax.lax.dot_general(
            x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _pallas_fwd(lhs, rhs, group_sizes, block_m, block_n, interpret):
    m, k = lhs.shape
    e, _, n = rhs.shape
    bm, bn = block_m, _fit(block_n, n)
    gid, total, dest, w = _plan(m, e, group_sizes, bm)
    xp = jnp.zeros((w * bm, k), lhs.dtype).at[dest].set(lhs)

    def xmap(ni, wi, gid_ref, tot_ref):
        del ni, gid_ref
        return jnp.minimum(wi, tot_ref[0] - 1), 0

    def wmap(ni, wi, gid_ref, tot_ref):
        return gid_ref[jnp.minimum(wi, tot_ref[0] - 1)], 0, ni

    def omap(ni, wi, gid_ref, tot_ref):
        del gid_ref
        return jnp.minimum(wi, tot_ref[0] - 1), ni

    yp = pl.pallas_call(
        _fwd_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n // bn, w),
            in_specs=[pl.BlockSpec((bm, k), xmap),
                      pl.BlockSpec((1, k, bn), wmap)],
            out_specs=pl.BlockSpec((bm, bn), omap)),
        out_shape=jax.ShapeDtypeStruct((w * bm, n), lhs.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
    )(gid, total.reshape(1), xp, rhs)
    return yp[dest]


def _dw_kernel(gid_ref, tot_ref, x_ref, g_ref, o_ref):
    wi = pl.program_id(2)

    @pl.when(wi < tot_ref[0])
    def _():
        contrib = jax.lax.dot_general(
            x_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        first = (wi == 0) | (gid_ref[wi] != gid_ref[jnp.maximum(wi - 1, 0)])

        @pl.when(first)
        def _():
            o_ref[0] = contrib

        @pl.when(~first)
        def _():
            o_ref[0] += contrib


def _pallas_dw(lhs, g, group_sizes, block_m, block_n, block_k, interpret):
    """drhs[e] = lhs[seg(e)].T @ g[seg(e)] — row tiles innermost so each
    expert's output block accumulates across consecutive revisits."""
    m, k = lhs.shape
    n = g.shape[1]
    e = group_sizes.shape[0]
    bm, bk, bn = block_m, _fit(block_k, k), _fit(block_n, n)
    gid, total, dest, w = _plan(m, e, group_sizes, bm)
    xp = jnp.zeros((w * bm, k), lhs.dtype).at[dest].set(lhs)
    gp = jnp.zeros((w * bm, n), g.dtype).at[dest].set(g)

    def xmap(ki, ni, wi, gid_ref, tot_ref):
        del ni, gid_ref
        return jnp.minimum(wi, tot_ref[0] - 1), ki

    def gmap(ki, ni, wi, gid_ref, tot_ref):
        del ki, gid_ref
        return jnp.minimum(wi, tot_ref[0] - 1), ni

    def omap(ki, ni, wi, gid_ref, tot_ref):
        return gid_ref[jnp.minimum(wi, tot_ref[0] - 1)], ki, ni

    dw = pl.pallas_call(
        _dw_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(k // bk, n // bn, w),
            in_specs=[pl.BlockSpec((bm, bk), xmap),
                      pl.BlockSpec((bm, bn), gmap)],
            out_specs=pl.BlockSpec((1, bk, bn), omap)),
        out_shape=jax.ShapeDtypeStruct((e, k, n), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL,
                                 pltpu.ARBITRARY)),
        interpret=interpret,
    )(gid, total.reshape(1), xp, gp)
    # blocks of never-visited (empty) experts are uninitialised memory
    return jnp.where((group_sizes > 0)[:, None, None], dw, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm(lhs, rhs, group_sizes, block_m, block_n, block_k, interpret):
    return _pallas_fwd(lhs, rhs, group_sizes, block_m, block_n, interpret)


def _gmm_fwd(lhs, rhs, group_sizes, block_m, block_n, block_k, interpret):
    out = _pallas_fwd(lhs, rhs, group_sizes, block_m, block_n, interpret)
    return out, (lhs, rhs, group_sizes)


def _gmm_bwd(block_m, block_n, block_k, interpret, res, g):
    lhs, rhs, group_sizes = res
    dlhs = _pallas_fwd(g, rhs.transpose(0, 2, 1).astype(rhs.dtype),
                       group_sizes, block_m, block_n, interpret)
    drhs = _pallas_dw(lhs, g, group_sizes, block_m, block_n, block_k,
                      interpret).astype(rhs.dtype)
    return dlhs, drhs, np.zeros(group_sizes.shape, _float0)


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


# ------------------------------------------------------------------ public
def grouped_matmul(lhs, rhs, group_sizes, *, block_m=DEFAULT_BLOCK_M,
                   block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
                   interpret=None, impl=None):
    """Ragged grouped matmul: ``out[r] = lhs[r] @ rhs[expert(r)]``.

    Args:
      lhs: ``[m, k]`` rows sorted so each expert's tokens are contiguous;
        ``sum(group_sizes)`` must equal ``m`` (rows past the ragged total
        produce unspecified output — callers that pad must mask).
      rhs: ``[experts, k, n]`` per-expert weights.
      group_sizes: ``[experts]`` int rows per expert (traced; zeros fine).
      interpret: run the Pallas kernel in interpreter mode; ``None`` picks
        interpret off-TPU (only consulted when ``impl="pallas"``).
      impl: ``"pallas"`` | ``"xla"`` | ``"dense"``; ``None`` auto-selects
        pallas on TPU and the xla tile-batch path elsewhere.

    Returns ``[m, n]`` in ``lhs.dtype`` (f32 accumulation on the MXU).
    """
    if lhs.ndim != 2 or rhs.ndim != 3 or rhs.shape[1] != lhs.shape[1]:
        raise ValueError(f"bad grouped_matmul shapes {lhs.shape} {rhs.shape}")
    if group_sizes.shape != (rhs.shape[0],):
        raise ValueError(f"group_sizes {group_sizes.shape} != "
                         f"({rhs.shape[0]},)")
    if not grouped_gemm_enabled():
        impl = "dense"
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "dense":
        return grouped_matmul_reference(lhs, rhs, group_sizes)
    if impl == "xla":
        # XLA tiles need no MXU alignment — shrink them until the
        # per-expert padding waste (up to experts*block_m rows) stops
        # dominating the ~m useful rows, or decode-sized calls pay the
        # dense path's experts*capacity bill all over again
        bm = block_m
        while bm > 8 and rhs.shape[0] * bm > lhs.shape[0]:
            bm //= 2
        return _xla_grouped(lhs, rhs, group_sizes.astype(jnp.int32), bm)
    if impl != "pallas":
        raise ValueError(f"unknown grouped_matmul impl {impl!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _gmm(lhs, rhs, group_sizes.astype(jnp.int32),
                block_m, block_n, block_k, bool(interpret))
