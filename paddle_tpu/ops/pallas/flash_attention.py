"""Pallas TPU flash attention (ref capability: ``paddle/phi/kernels/fusion/
flash_attn`` — the CUDA flash-attention kernel family).

TPU-first design (not a CUDA translation):
  * grid (batch*heads, q_blocks, kv_blocks) with the kv dimension iterated
    fastest — the output tile stays resident in VMEM across the kv sweep
    (Pallas keeps revisited blocks live), so the online-softmax accumulator
    never round-trips HBM.
  * fp32 accumulation in VMEM scratch; bf16 inputs feed the MXU directly.
  * backward = two kernels (dq-major and dkv-major sweeps) from saved
    (O, logsumexp), the standard flash-2 recomputation strategy.
  * causal blocks that are fully masked are skipped with @pl.when — the
    sweep does ~half the FLOPs for causal attention.
  * sliding-window (Mistral) runs on a BANDED grid: each q block's k-axis
    only spans its band (index_map offsets the block index), so both the
    FLOPs and the K/V DMA traffic are O(S*window), not O(S^2).

Layout: [B, S, H, D] at the API (reference flash_attention convention);
kernels run on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Swept on v5e (benchmarks/_perf_blocks.py, B4 S2048 H16 D128 causal):
# 128/128 ran 9.9ms fwd / 29.6ms fwd+bwd; 512/1024 runs 4.5 / 14.0 —
# a single 128^3 MXU issue per grid step can't hide the loop overhead.
# (1024/1024 measured equal within noise; 512 keeps the q tile usable
# at shorter sequence lengths.)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30
_float0 = jax.dtypes.float0

# Declaring the (batch-head, major, minor) grid as (parallel, parallel,
# arbitrary) lets Mosaic pipeline DMAs across grid steps instead of
# serialising them. Measured on v5e (benchmarks/_perf_banded.py, S=4096
# w=1024, dispatch floor subtracted): full causal 3.25ms -> 0.92ms, banded
# 2.12ms -> 0.77ms — and only WITH this declared does the banded O(S*W)
# grid actually beat full causal on-chip (r3 finding: 6.5x slower without).
# CompilerParams was TPUCompilerParams before the pallas API rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_GRID_SEMANTICS = _CompilerParams(
    dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY))


def _band_mask(s, i, j, block_q, block_k, causal, window, q_off, klen=None,
               sk=None):
    """Apply causal/sliding-window banding and (padded-varlen) key-length
    masking to a score tile. ``q_off`` (= sk - sq) aligns query positions to
    the END of the key axis so a short query block (KV-cache decode) sees
    the whole prefix. ``klen`` (traced scalar) masks keys >= the row's valid
    length — the reference's padded/varlen flash_attn capability. With
    klen AND q_off > 0 (decode against a PADDED cache, flash-attn's
    cache_seqlens form) query positions end-align to the row's valid
    length: position of query i is ``klen - sq + i``, so the whole
    computation equals a solo call against the trimmed cache."""
    off = _q_offset(q_off, klen, sk) if sk is not None else q_off
    q_idx = off + i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = q_idx >= k_idx if causal else (q_idx == q_idx)
    if window is not None:
        keep &= (q_idx - k_idx) < window
    if klen is not None:
        keep &= k_idx < klen
    return jnp.where(keep, s, _NEG_INF)


def _block_live(i, j, block_q, block_k, causal, window, q_off, klen=None):
    """Predicate: tile (i, j) has any unmasked entry — causal upper bound,
    with a window a lower band bound (skip tiles fully below it), and with
    varlen a key-length bound (skip tiles entirely in the padding)."""
    live = jnp.asarray(True)
    if causal:
        live &= j * block_k <= q_off + i * block_q + block_q - 1
    if window is not None:
        live &= q_off + i * block_q - (j * block_k + block_k - 1) < window
    if klen is not None:
        live &= j * block_k < klen
    return live


def _q_offset(q_off, klen, sk):
    """Query-position offset shared by the masks and ALiBi: buffer-end
    alignment (``sk - sq``) normally; with ``kv_lens`` AND a short query
    block (``q_off > 0``, decode against a PADDED cache) positions
    end-align to the row's VALID length (``klen - sq``) — ONE rule, so the
    bias and the masks can never disagree."""
    if klen is None or q_off == 0:
        return q_off
    return q_off + klen - sk


def _alibi_add(s, slope, i, j, block_q, block_k, a_off, causal):
    """Fused ALiBi, computed from iota IN-KERNEL — the O(S^2) bias tensor
    the XLA path materialises never exists here (the flash-attn CUDA
    kernel's alibi_slopes capability, TPU-style). Causal: the standard
    ``-slope * (q_pos - k_pos)`` decay; non-causal: symmetric
    ``-slope * |q_pos - k_pos|`` (flash-attn's bidirectional form).
    ``a_off`` aligns query positions: ``sk - sq`` for decode against an
    un-padded cache, ``klen - sq`` (traced) when ``kv_lens`` marks the
    valid cache length."""
    q_idx = a_off + i * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           s.shape, 0)
    k_idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    d = (k_idx - q_idx).astype(jnp.float32)
    return s + slope * (d if causal else -jnp.abs(d))


def _kv_row_index(kv_rep):
    """Index map factory for K/V block specs: q row b reads kv row
    b // kv_rep (identity when there is no GQA — keeps the non-GQA path
    free of the division)."""
    if kv_rep == 1:
        return lambda b, second, third: (b, third, 0)
    return lambda b, second, third: (b // kv_rep, third, 0)


def _band_j_start(i, block_q, block_k, window, q_off):
    """First k-block index in the band of q-block i (clamped to 0)."""
    return jnp.maximum(0, (i * block_q + q_off - window + 1) // block_k)


def _band_i_start(j, block_q, block_k, q_off):
    """First q-block index whose band reaches k-block j (clamped to 0)."""
    return jnp.maximum(0, (j * block_k - q_off) // block_q)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest,
                scale, causal, window, q_off, sk, block_q, block_k, nk,
                banded, nsteps, has_lens, has_slopes):
    rest = list(rest)
    lens_ref = rest.pop(0) if has_lens else None
    slopes_ref = rest.pop(0) if has_slopes else None
    o_ref, lse_ref, acc, m_sc, l_sc = rest
    b = pl.program_id(0)
    # lens/slopes ride whole-array in SMEM (a [BH, 1] VMEM block would
    # violate the (8, 128) tile rule); index by the batch-head grid row
    klen = lens_ref[b, 0] if has_lens else None
    i, jl = pl.program_id(1), pl.program_id(2)
    # banded grid: the j-axis is a window-relative offset from the first
    # live k block of this q block; full grid: jl IS the k block index
    j = _band_j_start(i, block_q, block_k, window, q_off) + jl if banded else jl

    @pl.when(jl == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    def compute():
        q = q_ref[0]  # [Bq, D]
        k = k_ref[0]  # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_slopes:
            s = _alibi_add(s, slopes_ref[b, 0], i, j, block_q, block_k,
                           _q_offset(q_off, klen, sk), causal)
        if causal or window is not None or has_lens:
            s = _band_mask(s, i, j, block_q, block_k, causal, window, q_off,
                           klen, sk)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_sc[:, 0] = l_sc[:, 0] * corr + jnp.sum(p, axis=1)
        m_sc[:, 0] = m_new
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv

    if banded:
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen) & (j < nk))(compute)
    elif causal or has_lens:
        # block (i, j) has any unmasked entry iff j*Bk <= i*Bq + Bq - 1
        # (windowed: not entirely below the band; varlen: not all padding)
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen))(compute)
    else:
        compute()

    @pl.when(jl == nsteps - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:], 1e-30)  # [Bq, 1]
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        # lse is [Bq, 1]: kept 2D with q on the sublane dim so the block
        # tiling is TPU-legal and it broadcasts against [Bq, Bk] scores.
        # Fully-masked rows (q in the padding of a varlen batch): l == 0 —
        # emit lse = 0 so the backward's exp(s - lse) underflows to 0
        # instead of exploding (s = -1e30, a real lse would be ~-1e30 too).
        lse = m_sc[:] + jnp.log(l)
        if has_lens:
            lse = jnp.where(l_sc[:] > 0, lse, 0.0)
        lse_ref[0] = lse.astype(lse_ref.dtype)


def _flash_fwd(q, k, v, lens, slopes, *, scale, causal, window, kv_rep,
               block_q, block_k, interpret):
    bh, s, d = q.shape
    sk = k.shape[1]
    q_off = sk - s  # align queries to the end of the key axis (decode)
    has_lens = lens is not None
    has_slopes = slopes is not None
    # GQA: k/v carry bh/kv_rep batch-head rows; q row b reads kv row
    # b // kv_rep via the index map — no repeated K/V is ever materialised
    nq, nk = pl.cdiv(s, block_q), pl.cdiv(sk, block_k)
    # windowed-causal: visit only the k blocks inside each q block's band —
    # the DMA pipeline then moves O(S*window) bytes, not O(S^2)
    banded = window is not None and causal and window < sk
    if banded:
        nsteps = min(nk, pl.cdiv(window + block_q - 1, block_k) + 1)

        def kv_index(b, i, jl):
            j = _band_j_start(i, block_q, block_k, window, q_off) + jl
            return (b // kv_rep, jnp.minimum(j, nk - 1), 0)
    else:
        nsteps = nk
        kv_index = _kv_row_index(kv_rep)
    grid = (bh, nq, nsteps)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               window=window, q_off=q_off, sk=sk,
                               block_q=block_q,
                               block_k=block_k, nk=nk, banded=banded,
                               nsteps=nsteps, has_lens=has_lens,
                               has_slopes=has_slopes)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    args = [q, k, v]
    if has_lens:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(lens)
    if has_slopes:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(slopes)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(*args)
    return out, lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, window, q_off, sk, block_q, block_k, nk,
               banded, nsteps, has_lens, has_slopes):
    rest = list(rest)
    lens_ref = rest.pop(0) if has_lens else None
    slopes_ref = rest.pop(0) if has_slopes else None
    dq_ref, dq_acc = rest
    b = pl.program_id(0)
    klen = lens_ref[b, 0] if has_lens else None
    i, jl = pl.program_id(1), pl.program_id(2)
    j = _band_j_start(i, block_q, block_k, window, q_off) + jl if banded else jl

    @pl.when(jl == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_slopes:
            s = _alibi_add(s, slopes_ref[b, 0], i, j, block_q, block_k,
                           _q_offset(q_off, klen, sk), causal)
        if causal or window is not None or has_lens:
            s = _band_mask(s, i, j, block_q, block_k, causal, window, q_off,
                           klen, sk)
        p = jnp.exp(s - lse_ref[0])  # lse_ref[0]: [Bq, 1]
        dp = jax.lax.dot_general(do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if banded:
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen) & (j < nk))(compute)
    elif causal or has_lens:
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen))(compute)
    else:
        compute()

    @pl.when(jl == nsteps - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, window, q_off, sk, block_q,
                block_k, nq, banded, nsteps, has_lens, has_slopes):
    rest = list(rest)
    lens_ref = rest.pop(0) if has_lens else None
    slopes_ref = rest.pop(0) if has_slopes else None
    dk_ref, dv_ref, dk_acc, dv_acc = rest
    b = pl.program_id(0)
    klen = lens_ref[b, 0] if has_lens else None
    j, il = pl.program_id(1), pl.program_id(2)  # kv-major: q iterated fastest
    i = _band_i_start(j, block_q, block_k, q_off) + il if banded else il

    @pl.when(il == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_slopes:
            s = _alibi_add(s, slopes_ref[b, 0], i, j, block_q, block_k,
                           _q_offset(q_off, klen, sk), causal)
        if causal or window is not None or has_lens:
            s = _band_mask(s, i, j, block_q, block_k, causal, window, q_off,
                           klen, sk)
        p = jnp.exp(s - lse_ref[0])  # [Bq, Bk]; lse_ref[0]: [Bq, 1]
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale  # [Bq, Bk]
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if banded:
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen) & (i < nq))(compute)
    elif causal or has_lens:
        # varlen: k blocks fully in the padding keep zero dk/dv (init runs
        # on il==0 regardless, so the outputs are well-defined zeros)
        pl.when(_block_live(i, j, block_q, block_k, causal, window, q_off,
                            klen))(compute)
    else:
        compute()

    @pl.when(il == nsteps - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, window, kv_rep, block_q, block_k,
               interpret):
    q, k, v, lens, slopes, out, lse = res
    bh, s, d = q.shape
    sk = k.shape[1]
    bh_kv = k.shape[0]
    q_off = sk - s
    has_lens = lens is not None
    has_slopes = slopes is not None
    nq, nk = pl.cdiv(s, block_q), pl.cdiv(sk, block_k)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, S, 1] to match lse layout

    banded = window is not None and causal and window < sk
    if banded:
        nk_steps = min(nk, pl.cdiv(window + block_q - 1, block_k) + 1)
        nq_steps = min(nq, pl.cdiv(window + block_k - 1, block_q) + 1)

        def kv_index_dq(b, i, jl):
            j = _band_j_start(i, block_q, block_k, window, q_off) + jl
            return (b // kv_rep, jnp.minimum(j, nk - 1), 0)

        def q_index_dkv(b, j, il):
            i = _band_i_start(j, block_q, block_k, q_off) + il
            return (b, jnp.minimum(i, nq - 1), 0)
    else:
        nk_steps, nq_steps = nk, nq

        kv_index_dq = _kv_row_index(kv_rep)

        def q_index_dkv(b, j, il):
            return (b, il, 0)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_index_dq),
        pl.BlockSpec((1, block_k, d), kv_index_dq),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = [q, k, v, g, lse, delta]
    if has_lens:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_args.append(lens)
    if has_slopes:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_args.append(slopes)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          window=window, q_off=q_off, sk=sk,
                          block_q=block_q,
                          block_k=block_k, nk=nk, banded=banded,
                          nsteps=nk_steps, has_lens=has_lens,
                          has_slopes=has_slopes),
        grid=(bh, nq, nk_steps),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(*dq_args)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), q_index_dkv),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: _kv_row_index(kv_rep)(b, i, j)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: _kv_row_index(kv_rep)(b, i, j)),
        pl.BlockSpec((1, block_q, d), q_index_dkv),
        pl.BlockSpec((1, block_q, 1), q_index_dkv),
        pl.BlockSpec((1, block_q, 1), q_index_dkv),
    ]
    dkv_args = [q, k, v, g, lse, delta]
    if has_lens:
        dkv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_args.append(lens)
    if has_slopes:
        dkv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_args.append(slopes)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          window=window, q_off=q_off, sk=sk,
                          block_q=block_q,
                          block_k=block_k, nq=nq, banded=banded,
                          nsteps=nq_steps, has_lens=has_lens,
                          has_slopes=has_slopes),
        grid=(bh, nk, nq_steps),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(*dkv_args)
    if kv_rep > 1:
        # per-q-head partials -> sum over each kv group (rows are contiguous)
        dk = dk.reshape(bh_kv, kv_rep, sk, d).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(bh_kv, kv_rep, sk, d).sum(axis=1).astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, lens, slopes, scale, causal, window, kv_rep, block_q,
           block_k, interpret):
    out, _ = _flash_fwd(q, k, v, lens, slopes, scale=scale, causal=causal,
                        window=window, kv_rep=kv_rep, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return out


def _flash_vjp_fwd(q, k, v, lens, slopes, scale, causal, window, kv_rep,
                   block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, lens, slopes, scale=scale, causal=causal,
                          window=window, kv_rep=kv_rep, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    return out, (q, k, v, lens, slopes, out, lse)


def _flash_vjp_bwd(scale, causal, window, kv_rep, block_q, block_k, interpret,
                   res, g):
    dq, dk, dv = _flash_bwd(res, g, scale=scale, causal=causal, window=window,
                            kv_rep=kv_rep, block_q=block_q, block_k=block_k,
                            interpret=interpret)
    lens, slopes = res[3], res[4]
    dlens = None if lens is None else np.zeros(lens.shape, _float0)
    # ALiBi slopes are a fixed head geometry, not learned (flash-attn's
    # alibi_slopes contract) — zero cotangent
    dslopes = None if slopes is None else jnp.zeros_like(slopes)
    return dq, dk, dv, dlens, dslopes


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    window: int | None = None, kv_lens=None,
                    alibi_slopes=None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """q,k,v: [B, S, H, D] (reference flash_attention layout). GQA supported
    natively: K/V may carry fewer heads (H % H_kv == 0); the kernel reads kv
    row b//rep through the index map, so no repeated K/V is materialised.
    ``window``: causal sliding-window size (Mistral-style; token i attends
    to [i-window+1, i]) — the banded grid skips out-of-band tiles AND their
    DMAs, so long-sequence cost is O(S*window).
    ``kv_lens``: [B] int32 valid key lengths — the padded-varlen path (ref
    ``flash_attn_varlen`` capability): keys >= the row's length are masked
    in-kernel and fully-padded key blocks are skipped, with no O(S^2) mask
    tensor. NOTE query rows in the padding are NOT masked q-side: under
    causal+kv_lens a padded query row still attends every key < its row's
    klen, so its output is unspecified garbage — callers MUST mask those
    rows out of the loss (zero upstream cotangent), which is also what
    makes their grads exactly zero.
    ``alibi_slopes``: [H] (or [B, H]) positive ALiBi slopes m — the kernel
    adds ``-m * (q_pos - k_pos)`` to the scores, computed from iota IN the
    tile (the flash-attn ``alibi_slopes`` capability): no O(S^2) bias
    tensor exists, unlike the XLA additive-mask path. Slopes are fixed
    head geometry (not learned): their cotangent is zero."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    h_kv = k.shape[2]
    if h % h_kv != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    kv_rep = h // h_kv
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if window is not None and kv_lens is not None and s != sk:
        # the banded grid's block-liveness pruning is computed from the
        # buffer-end offset; under klen-aligned decode positions it could
        # skip live tiles — refuse rather than silently drop attention
        raise NotImplementedError(
            "window + kv_lens with sq != sk (windowed decode against a "
            "padded cache) is not supported; trim the cache or use the "
            "paged decode kernel")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else d ** -0.5

    def _fit(blk, n):
        # largest power-of-two divisor step down from the requested block:
        # a non-dividing block would pad the grid and the padded key
        # columns (k_idx in [sk, nk*bk)) pass the causal mask for late
        # query rows — garbage would enter the softmax
        blk = min(blk, n)
        while n % blk:
            blk //= 2
        return max(blk, 1)

    bq = _fit(block_q, s)
    bk = _fit(block_k, sk)

    # Non-128-divisible lengths would otherwise step the tile down to a
    # tiny divisor (s=1000 -> bq=8 — ~64x smaller MXU tiles than the
    # tuned default): pad to an aligned length and mask/slice the tail
    # instead. Padded KEY columns are masked causally (equal q/k padding
    # keeps q_off = 0, so every real row's pad columns sit strictly above
    # the diagonal) or by the kv_lens machinery (klen <= sk always masks
    # them; `_q_offset`'s klen-based alignment is invariant under k-only
    # padding). Padded QUERY rows compute junk that is sliced off — no
    # padded row is ever fully masked, so no NaN leaks into the bwd
    # matmuls via their zero cotangent. Skipped for windowed decode
    # (s != sk): masking pads there needs kv_lens, a combo the banded
    # grid refuses above.
    pad_q = pad_k = 0
    if (((bq < 128 and s > 128) or (bk < 128 and sk > 128))
            and not (window is not None and s != sk)):
        tq = min(block_q, 1 << max(7, s.bit_length() - 1))
        tk = min(block_k, 1 << max(7, sk.bit_length() - 1))
        if s == sk:
            t = max(tq, tk)           # one pad aligns both (powers of 2)
            pad_q = pad_k = (-s) % t
            if not causal and kv_lens is None:
                kv_lens = jnp.full((b,), sk, jnp.int32)
        else:
            # end-aligned query rows (decode): pad K only; bq keeps the
            # _fit value (decode sq is small and usually aligned). If sk
            # is already aligned (the trigger was a tiny bq) there is
            # nothing to pad — forcing kv_lens then would buy the lens
            # masking overhead for no tile improvement.
            pad_k = (-sk) % tk
            if pad_k and kv_lens is None:
                kv_lens = jnp.full((b,), sk, jnp.int32)
        if pad_q:
            q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        if pad_k:
            k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        bq = _fit(block_q, s + pad_q)
        bk = _fit(block_k, sk + pad_k)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(-1, x.shape[1], d)

    lens = None
    if kv_lens is not None:
        # [B] -> [B*H, 1]: one scalar per q batch-head row
        lens = jnp.repeat(jnp.asarray(kv_lens, jnp.int32), h)[:, None]
    slopes = None
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        # [H] or [B, H] -> [B*H, 1]: one scalar per q batch-head row
        slopes = jnp.broadcast_to(slopes.reshape(-1, h), (b, h)
                                  ).reshape(-1)[:, None]
    out = _flash(to_bh(q), to_bh(k), to_bh(v), lens, slopes, scale, causal,
                 window, kv_rep, bq, bk, interpret)
    out = jnp.swapaxes(out.reshape(b, h, s + pad_q, d), 1, 2)
    return out[:, :s] if pad_q else out
