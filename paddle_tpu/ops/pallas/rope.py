"""Pallas fused rotary embedding (ref: ``paddle/phi/kernels/fusion/
fused_rope``). Applies rotate-half RoPE to q and k in one VMEM pass —
avoids materialising the rotated halves in HBM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[0].astype(jnp.float32)          # [H, D] one (b, s) slice? -> see specs
    cos = cos_ref[0].astype(jnp.float32)      # [1, D/2]
    sin = sin_ref[0].astype(jnp.float32)
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    o = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    o_ref[0] = o.astype(o_ref.dtype)


def fused_rope(x, cos, sin, interpret=None):
    """x: [B, S, H, D]; cos/sin: [S, D/2]. Falls back to jnp when the shape
    doesn't justify a kernel launch."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = x.shape
    xr = x.reshape(b * s, h, d)
    cs = jnp.broadcast_to(cos[None], (b, s, cos.shape[-1])).reshape(b * s, 1, -1)
    sn = jnp.broadcast_to(sin[None], (b, s, sin.shape[-1])).reshape(b * s, 1, -1)
    out = pl.pallas_call(
        _rope_kernel,
        grid=(b * s,),
        in_specs=[pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
                  pl.BlockSpec((1, 1, d // 2), lambda i: (i, 0, 0)),
                  pl.BlockSpec((1, 1, d // 2), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * s, h, d), x.dtype),
        interpret=interpret,
    )(xr, cs, sn)
    return out.reshape(b, s, h, d)
