"""Paged-attention decode kernel (ref capability: PaddleNLP ``llm``
block-attention / ``paddle/phi/kernels/fusion/gpu/
fused_multi_transformer_op.cu`` block KV cache).

TPU-first design: the KV cache is a POOL of fixed-size blocks
([num_blocks, block_size, H_kv, D]) shared by all sequences; each sequence
owns a row of ``block_tables`` (pool indices). Decode attention reads a
sequence's blocks pool-directly via a scalar-prefetched block table
(``pltpu.PrefetchScalarGridSpec``) — the kernel's index_map picks the
physical block for each grid step, so the gathered K/V is NEVER
materialised: HBM holds pool ≈ Σ actual lengths (not B × max_len) and VMEM
holds one block at a time.

Layout: q [B, H, D] (one decode token per sequence), pool
[N_blocks, block_size, H_kv, D], block_tables [B, max_blocks], lens [B].
Unused table slots must hold a VALID pool index (0 is fine): the index map
still reads them, the compute is masked off by ``lens``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# CompilerParams was TPUCompilerParams before the pallas API rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                         acc, m_sc, l_sc, *, block_size, scale, max_blocks,
                         window):
    """Grid (B*H, max_blocks); block j of row bh is pool block
    tables[bh, j] (resolved by the BlockSpec index maps)."""
    bh = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[0, 0] = _NEG_INF
        l_sc[0, 0] = 0.0

    seq_len = lens_ref[bh, 0]
    n_live = pl.cdiv(seq_len, block_size)
    live = j < n_live
    if window is not None:
        # sliding window: only the last `window` positions are visible —
        # blocks entirely below seq_len - window are dead
        live &= (j + 1) * block_size > seq_len - window

    @pl.when(live)
    def _compute():
        q = q_ref[0]          # [1, D] — this head's single query row
        k = k_ref[0, 0]       # [block_size, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q.astype(jnp.float32), k.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        # mask positions beyond the sequence length within the last block
        pos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = pos < seq_len
        if window is not None:
            keep &= pos >= seq_len - window
        s = jnp.where(keep, s, _NEG_INF)
        m_prev = m_sc[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_sc[0, 0] = l_sc[0, 0] * corr + jnp.sum(p)
        m_sc[0, 0] = m_new
        pv = jax.lax.dot_general(p, v.astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr + pv

    @pl.when(j == max_blocks - 1)
    def _finalize():
        o_ref[0] = (acc[:] / jnp.maximum(l_sc[0, 0], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables, lens, *,
                                  scale=None, window=None,
                                  interpret: bool | None = None):
    """One decode step over block tables. q: [B, H, D];
    k_pool/v_pool: [N, bs, H_kv, D]; block_tables: [B, max_blocks] int32;
    lens: [B] int32 (current lengths INCLUDING the new token, whose K/V
    must already be written to the pool). Returns [B, H, D]."""
    b, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    kv_rep = h // h_kv
    max_blocks = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # one grid row per (sequence, q head)
    qf = q.reshape(b * h, 1, d)
    tables_bh = jnp.repeat(block_tables.astype(jnp.int32), h, axis=0)
    lens_bh = jnp.repeat(lens.astype(jnp.int32), h)[:, None]

    # pool per kv head: [H_kv, N, bs, D] — one (head, block) tile is a
    # contiguous [bs, D] slice
    kp = jnp.moveaxis(k_pool, 2, 0)
    vp = jnp.moveaxis(v_pool, 2, 0)

    def kv_index(bh, j, tables, lens):
        # unused slots hold the OOB sentinel (num_blocks) — clamp; their
        # compute is masked off by lens in the kernel
        return ((bh % h) // kv_rep, jnp.minimum(tables[bh, j], n - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bh, j, t, l: (bh, 0, 0)),
            pl.BlockSpec((1, 1, bs, d), kv_index),
            pl.BlockSpec((1, 1, bs, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda bh, j, t, l: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            # running max / denom are SCALARS: Mosaic rejects scalar stores
            # to VMEM, so they live in SMEM scratch
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SMEM((1, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, block_size=bs,
                               scale=scale, max_blocks=max_blocks,
                               window=window)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), q.dtype),
        # (sequence-head, block) grid: rows are independent; declaring the
        # row axis parallel lets Mosaic pipeline pool-block DMAs across rows
        # (measured 3.5x on the flash grids — benchmarks/_perf_banded.py)
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
    )(tables_bh, lens_bh, qf, kp, vp)
    return out.reshape(b, h, d)


def paged_decode_attention_xla(q, k_pool, v_pool, block_tables, lens, *,
                               scale=None, window=None):
    """Gather-based reference path (CPU tests / fallback). Same contract as
    the Pallas kernel; materialises the gathered K/V transiently."""
    b, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    scale = scale if scale is not None else d ** -0.5
    max_blocks = block_tables.shape[1]
    # clamp the OOB padding sentinel (= num_blocks): jnp.take's fill mode
    # would yield NaN rows, which the length mask cannot launder
    tables = jnp.minimum(block_tables, n - 1)
    k = jnp.take(k_pool, tables, axis=0)  # [B, MB, bs, H_kv, D]
    v = jnp.take(v_pool, tables, axis=0)
    k = k.reshape(b, max_blocks * bs, h_kv, d)
    v = v.reshape(b, max_blocks * bs, h_kv, d)
    if h_kv != h:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, :]
    keep = pos < lens[:, None, None]
    if window is not None:
        keep &= pos >= (lens[:, None, None] - window)
    s = jnp.where(keep, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32)).astype(q.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lens, *,
                           scale=None, window=None,
                           interpret: bool | None = None):
    """Dispatch: Pallas on TPU (pool-direct block reads), XLA elsewhere.
    ``window``: sliding-window bound — only the last `window` positions
    are visible (Mistral decode semantics)."""
    if jax.default_backend() == "tpu":
        try:
            return paged_decode_attention_pallas(
                q, k_pool, v_pool, block_tables, lens, scale=scale,
                window=window, interpret=interpret)
        except Exception:
            pass
    return paged_decode_attention_xla(q, k_pool, v_pool, block_tables, lens,
                                      scale=scale, window=window)
