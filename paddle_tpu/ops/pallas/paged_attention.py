"""Paged-attention kernels (ref capability: PaddleNLP ``llm``
block-attention / ``paddle/phi/kernels/fusion/gpu/
fused_multi_transformer_op.cu`` block KV cache).

TPU-first design: the KV cache is a POOL of fixed-size blocks
([num_blocks, block_size, H_kv, D]) shared by all sequences; each sequence
owns a row of ``block_tables`` (pool indices). Attention reads a
sequence's blocks pool-directly via a scalar-prefetched block table
(``pltpu.PrefetchScalarGridSpec``) — the kernel's index_map picks the
physical block for each grid step, so the gathered K/V is NEVER
materialised: HBM holds pool ≈ Σ actual lengths (not B × max_len) and VMEM
holds one block at a time.

Two kernels share that scheme:

* **decode** — q [B, H, D] (one token per sequence), grid
  (B*H, kv-block), lens [B] masking the ragged tail.
* **chunk** (ISSUE 11) — the ragged MULTI-query forward behind chunked
  prefill and the spec-decode ``(slots, k+1)`` verify batch: q
  [A, C, H, D] chunk queries at positions ``offsets[a] ..
  offsets[a]+chunk_lens[a]-1``, attending causally over the slot's whole
  pool prefix. Grid (A*H_kv, q-tile, kv-block); the H/H_kv query heads of
  a KV head fold into the q tile, so GQA needs no repeated K/V.

Unused table slots hold the OOB sentinel (= num_blocks): index maps clamp
it, the compute is masked off by the length scalars.

Dispatch functions (``paged_decode_attention`` /
``paged_chunk_attention``) pick Pallas on TPU and the XLA gather
reference elsewhere. A Pallas trace/lower failure is cached per process
(one ``warnings.warn`` + a ``serving_pallas_fallback_total{kernel}``
increment — NOT retried every call), and ``PT_PAGED_CHUNK=0`` force-kills
the chunk kernel (``=interpret`` forces the interpreted kernel off-TPU,
the engine-level parity mode).
"""
from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.observability.metrics import METRICS

# CompilerParams was TPUCompilerParams before the pallas API rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30

_PALLAS_FALLBACK = METRICS.counter(
    "serving_pallas_fallback_total",
    "paged-attention Pallas kernels that failed to trace/lower and were "
    "replaced by the XLA gather path for the rest of the process, by "
    "kernel (decode/chunk)",
    labelnames=("kernel",))

# kernel -> first failure, recorded by the dispatch functions: once a
# kernel fails to trace/lower on this process it is NOT retried on every
# call (the old bare ``except: pass`` re-paid the trace failure per
# dispatch and hid the downgrade entirely)
_pallas_disabled: dict[str, str] = {}

# trace-time breadcrumbs ("chunk:xla-forced", "chunk:pallas", ...): one
# entry per DISPATCH TRACE, so tests can assert which implementation a
# jitted program actually baked in (flipping PT_PAGED_CHUNK without
# clearing jit caches appends nothing — the stale trace is reused)
_trace_events: list[str] = []


def _note_trace(event: str):
    if len(_trace_events) >= 512:
        del _trace_events[:256]
    _trace_events.append(event)


def _disable_pallas(kernel: str, err: Exception):
    _pallas_disabled[kernel] = f"{type(err).__name__}: {err}"
    _PALLAS_FALLBACK.inc(kernel=kernel)
    warnings.warn(
        f"paged {kernel} attention: Pallas kernel failed to trace/lower "
        f"({type(err).__name__}: {err}); using the XLA gather path for "
        "the rest of the process", RuntimeWarning, stacklevel=3)


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                         block_size, scale, max_blocks, window, quantized,
                         partials, n_pool=0):
    """Grid (B*H, max_blocks); block j of row bh is pool block
    tables[bh, j] (resolved by the BlockSpec index maps). ``quantized``
    (static) adds two per-position scale refs after v_ref: the pool holds
    int8 and K/V are dequantized in-kernel (f32 multiply — the matmul
    already upcasts, so the bf16 trace is unchanged when off).
    ``partials`` (static) is the context-parallel output mode: instead
    of the normalised output, emit the raw online-softmax triple
    (acc, m, l) and skip table entries this shard does not own (the
    caller translated non-owned global block ids to the OOB sentinel) —
    the cross-shard merge renormalises. Off, the trace is byte-identical
    to the pre-cp kernel."""
    if quantized:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    if partials:
        o_ref, m_ref, l_ref, acc, m_sc, l_sc = rest
    else:
        o_ref, acc, m_sc, l_sc = rest
    bh = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[0, 0] = _NEG_INF
        l_sc[0, 0] = 0.0

    seq_len = lens_ref[bh, 0]
    n_live = pl.cdiv(seq_len, block_size)
    live = j < n_live
    if partials:
        # ownership mask: under cp the table row interleaves blocks of
        # every shard; non-owned entries were translated to the local
        # sentinel (= local num_blocks) and contribute NOTHING here —
        # their positions are covered by the owning shard's partial
        live &= tables_ref[bh, j] < n_pool
    if window is not None:
        # sliding window: only the last `window` positions are visible —
        # blocks entirely below seq_len - window are dead
        live &= (j + 1) * block_size > seq_len - window

    @pl.when(live)
    def _compute():
        q = q_ref[0]          # [1, D] — this head's single query row
        k = k_ref[0, 0].astype(jnp.float32)   # [block_size, D]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            # per-(position, head) absmax scales: [block_size, 1]
            # broadcasts over D
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q.astype(jnp.float32), k,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        # mask positions beyond the sequence length within the last block
        pos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = pos < seq_len
        if window is not None:
            keep &= pos >= seq_len - window
        s = jnp.where(keep, s, _NEG_INF)
        m_prev = m_sc[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_sc[0, 0] = l_sc[0, 0] * corr + jnp.sum(p)
        m_sc[0, 0] = m_new
        pv = jax.lax.dot_general(p, v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr + pv

    @pl.when(j == max_blocks - 1)
    def _finalize():
        if partials:
            # emit the raw triple; m/l lane-replicated (vector store —
            # scalar VMEM stores hit Mosaic layout restrictions)
            o_ref[0] = acc[:].astype(o_ref.dtype)
            m_ref[0] = jnp.full((1, 128), m_sc[0, 0], jnp.float32)
            l_ref[0] = jnp.full((1, 128), l_sc[0, 0], jnp.float32)
        else:
            o_ref[0] = (acc[:] / jnp.maximum(l_sc[0, 0], 1e-30)
                        ).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables, lens, *,
                                  scale=None, window=None, k_scale=None,
                                  v_scale=None, partials=False,
                                  interpret: bool | None = None):
    """One decode step over block tables. q: [B, H, D];
    k_pool/v_pool: [N, bs, H_kv, D]; block_tables: [B, max_blocks] int32;
    lens: [B] int32 (current lengths INCLUDING the new token, whose K/V
    must already be written to the pool). ``k_scale``/``v_scale``
    [N, bs, H_kv] f32 dequantize an int8 pool in-kernel (per-position,
    per-head absmax scales). Returns [B, H, D] — or, with
    ``partials=True`` (context parallelism), the un-normalised
    online-softmax triple (acc [B, H, D] f32, m [B, H] f32, l [B, H]
    f32) over the table entries < N only (non-owned entries hold the
    OOB sentinel and are skipped)."""
    b, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    kv_rep = h // h_kv
    max_blocks = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    quantized = k_scale is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # one grid row per (sequence, q head)
    qf = q.reshape(b * h, 1, d)
    tables_bh = jnp.repeat(block_tables.astype(jnp.int32), h, axis=0)
    lens_bh = jnp.repeat(lens.astype(jnp.int32), h)[:, None]

    # pool per kv head: [H_kv, N, bs, D] — one (head, block) tile is a
    # contiguous [bs, D] slice
    kp = jnp.moveaxis(k_pool, 2, 0)
    vp = jnp.moveaxis(v_pool, 2, 0)

    def kv_index(bh, j, tables, lens):
        # unused slots hold the OOB sentinel (num_blocks) — clamp; their
        # compute is masked off by lens in the kernel
        return ((bh % h) // kv_rep, jnp.minimum(tables[bh, j], n - 1), 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, d), lambda bh, j, t, l: (bh, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qf, kp, vp]
    if quantized:
        # scale pools ride the same index map as their int8 pools:
        # [H_kv, N, bs, 1], one lane per position
        in_specs += [pl.BlockSpec((1, 1, bs, 1), kv_index),
                     pl.BlockSpec((1, 1, bs, 1), kv_index)]
        operands += [jnp.moveaxis(k_scale, 2, 0)[..., None],
                     jnp.moveaxis(v_scale, 2, 0)[..., None]]

    out_idx = lambda bh, j, t, l: (bh, 0, 0)  # noqa: E731
    out_specs = pl.BlockSpec((1, 1, d), out_idx)
    out_shape = jax.ShapeDtypeStruct((b * h, 1, d), q.dtype)
    if partials:
        # acc in f32 (the merge renormalises before the dtype cast) plus
        # lane-replicated m/l rows
        out_specs = [out_specs,
                     pl.BlockSpec((1, 1, 128), out_idx),
                     pl.BlockSpec((1, 1, 128), out_idx)]
        out_shape = [jax.ShapeDtypeStruct((b * h, 1, d), jnp.float32),
                     jax.ShapeDtypeStruct((b * h, 1, 128), jnp.float32),
                     jax.ShapeDtypeStruct((b * h, 1, 128), jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, max_blocks),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            # running max / denom are SCALARS: Mosaic rejects scalar stores
            # to VMEM, so they live in SMEM scratch
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SMEM((1, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, block_size=bs,
                               scale=scale, max_blocks=max_blocks,
                               window=window, quantized=quantized,
                               partials=partials, n_pool=n)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # (sequence-head, block) grid: rows are independent; declaring the
        # row axis parallel lets Mosaic pipeline pool-block DMAs across rows
        # (measured 3.5x on the flash grids — benchmarks/_perf_banded.py)
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
    )(tables_bh, lens_bh, *operands)
    if partials:
        acc, m, l = out
        return (acc.reshape(b, h, d), m[:, 0, 0].reshape(b, h),
                l[:, 0, 0].reshape(b, h))
    return out.reshape(b, h, d)


def paged_decode_attention_xla(q, k_pool, v_pool, block_tables, lens, *,
                               scale=None, window=None, k_scale=None,
                               v_scale=None, partials=False):
    """Gather-based reference path (CPU tests / fallback). Same contract as
    the Pallas kernel; materialises the gathered K/V transiently.
    ``partials=True`` returns the (acc, m, l) triple over owned table
    entries only — bit-compatible with the Pallas partials mode."""
    b, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    scale = scale if scale is not None else d ** -0.5
    max_blocks = block_tables.shape[1]
    # clamp the OOB padding sentinel (= num_blocks): jnp.take's fill mode
    # would yield NaN rows, which the length mask cannot launder
    tables = jnp.minimum(block_tables, n - 1)
    k = jnp.take(k_pool, tables, axis=0)  # [B, MB, bs, H_kv, D]
    v = jnp.take(v_pool, tables, axis=0)
    if k_scale is not None:
        # int8 pool: gather the scale rows the same way and dequantize in
        # f32 (never downcast — the attention math below is f32 anyway)
        k = k.astype(jnp.float32) * jnp.take(k_scale, tables,
                                             axis=0)[..., None]
        v = v.astype(jnp.float32) * jnp.take(v_scale, tables,
                                             axis=0)[..., None]
    k = k.reshape(b, max_blocks * bs, h_kv, d)
    v = v.reshape(b, max_blocks * bs, h_kv, d)
    if h_kv != h:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, :]
    keep = pos < lens[:, None, None]
    if window is not None:
        keep &= pos >= (lens[:, None, None] - window)
    if partials:
        # ownership mask (cp): a clamped non-owned sentinel slot would
        # otherwise contribute a garbage block the position mask cannot
        # catch — only entries < N are this shard's
        keep = keep & jnp.repeat(block_tables < n, bs,
                                 axis=1)[:, None, :]
        s = jnp.where(keep, s, _NEG_INF)
        m = jnp.max(s, axis=-1)                       # [B, H]
        # the explicit keep multiply kills the all-masked degenerate row
        # (m == -1e30 -> exp(0) == 1 everywhere without it)
        p = jnp.exp(s - m[..., None]) * keep
        acc = jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32))
        return acc, m, jnp.sum(p, axis=-1)
    s = jnp.where(keep, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32)).astype(q.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lens, *,
                           scale=None, window=None, k_scale=None,
                           v_scale=None, partials=False,
                           interpret: bool | None = None):
    """Dispatch: Pallas on TPU (pool-direct block reads), XLA elsewhere.
    ``window``: sliding-window bound — only the last `window` positions
    are visible (Mistral decode semantics). ``k_scale``/``v_scale``
    [N, bs, H_kv] f32 mark an int8 pool — dequantize-on-read in both
    paths. ``partials=True`` (context parallelism) returns the raw
    (acc, m, l) online-softmax triple over OWNED table entries only
    (< N; non-owned entries hold the OOB sentinel) — the caller merges
    across shards. A Pallas failure downgrades this process to the XLA
    path permanently (cached, warned, counted — see ``_disable_pallas``)."""
    if k_scale is not None:
        # breadcrumb ONLY on the quantized branch, so bf16 traces stay
        # byte-identical to pre-quantization builds
        _note_trace("decode:int8-kv")
    if partials:
        _note_trace("decode:partials")
    if jax.default_backend() == "tpu" and "decode" not in _pallas_disabled:
        try:
            return paged_decode_attention_pallas(
                q, k_pool, v_pool, block_tables, lens, scale=scale,
                window=window, k_scale=k_scale, v_scale=v_scale,
                partials=partials, interpret=interpret)
        except Exception as e:
            _disable_pallas("decode", e)
    return paged_decode_attention_xla(q, k_pool, v_pool, block_tables, lens,
                                      scale=scale, window=window,
                                      k_scale=k_scale, v_scale=v_scale,
                                      partials=partials)


# --------------------------------------------------------- chunk kernel
# The ragged multi-query forward (ISSUE 11): chunked prefill writes C
# tokens per row at offsets[a]..offsets[a]+chunk_lens[a]-1 and each of
# them attends causally over the row's WHOLE pool prefix. The spec-decode
# verify batch is the same program at C = k+1. The q tile folds the
# H/H_kv query heads of one KV head (GQA without repeating K/V), and the
# kv-block axis walks the row's block table with dead tiles skipped:
# blocks past the causal frontier of a q tile (and past the row's live
# length) clamp their index map to the last live block, so Mosaic never
# issues a fresh DMA for them, and their compute is @pl.when-masked.

def _paged_chunk_kernel(tables_ref, offs_ref, cls_ref, q_ref, k_ref, v_ref,
                        *rest, block_size, scale, max_blocks, q_tile,
                        group, n_kv, window, quantized, partials,
                        n_pool=0):
    """Grid (A*H_kv, q-tiles, kv-blocks). Row r serves sequence
    a = r // n_kv, KV head r % n_kv; its q tile holds ``q_tile`` folded
    rows (folded row t = query position t // group, grouped head
    t % group). Online-softmax accumulation across the kv-block axis.
    ``quantized`` (static) adds two per-position scale refs after v_ref
    (int8 pool, dequantize in-kernel). ``partials`` (static, context
    parallelism): emit the raw (acc, m, l) triple instead of the
    normalised output and skip non-owned table entries (translated to
    the OOB sentinel by the caller)."""
    if quantized:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    if partials:
        o_ref, m_ref, l_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    r = pl.program_id(0)
    qt = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    a_idx = r // n_kv
    off = offs_ref[a_idx, 0]
    cl = cls_ref[a_idx, 0]
    row_len = off + cl                     # this row's live pool length
    n_live = pl.cdiv(row_len, block_size)
    q0 = qt * q_tile                       # first folded row of the tile
    last_q = off + (q0 + q_tile - 1) // group   # tile's last query position
    live = (j < n_live) & (q0 < cl * group)
    if partials:
        # ownership mask: non-owned table entries were translated to the
        # local sentinel — the owning shard's partial covers them
        live &= tables_ref[a_idx, j] < n_pool
    # causal dead-tile skip: a block whose FIRST key position is past the
    # tile's LAST query position contributes nothing
    live &= j * block_size <= last_q
    if window is not None:
        # sliding window: a block entirely below the tile's first query's
        # window is invisible to every query in the tile
        first_q = off + q0 // group
        live &= (j + 1) * block_size - 1 > first_q - window

    @pl.when(live)
    def _compute():
        q = q_ref[0]                       # [q_tile, D] folded queries
        k = k_ref[0, 0].astype(jnp.float32)    # [block_size, D]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]           # [block_size, 1] over D
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q.astype(jnp.float32), k,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        row_t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        qpos = off + (q0 + row_t) // group
        kpos = j * block_size + col
        # causal + ragged: key visible iff it is at/before the query AND
        # inside the row's live length; folded rows past chunk_lens*group
        # are padding (their tile output is discarded by the caller)
        keep = (kpos <= qpos) & (kpos < row_len)
        keep &= (q0 + row_t) < cl * group
        if window is not None:
            keep &= (qpos - kpos) < window
        s = jnp.where(keep, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if partials:
            # a row whose visible keys ALL live on other shards is fully
            # masked here: m_new == _NEG_INF and exp(s - m_new) == 1 —
            # the explicit keep multiply zeroes it so the merged triple
            # stays (acc=0, l=0) instead of garbage (cp=1 never hits
            # this: block 0 always holds visible keys for a real row)
            p = p * keep.astype(jnp.float32)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        pv = jax.lax.dot_general(p, v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr + pv

    @pl.when(j == max_blocks - 1)
    def _finalize():
        if partials:
            o_ref[0] = acc[:].astype(o_ref.dtype)
            m_ref[0] = m_scr[:]
            l_ref[0] = l_scr[:]
        else:
            # fully-masked rows (dead/padding) have l == 0: emit 0, not NaN
            o_ref[0] = (acc[:] / jnp.maximum(l_scr[:, :1], 1e-30)
                        ).astype(o_ref.dtype)


def paged_chunk_attention_pallas(q, k_pool, v_pool, block_tables, offsets,
                                 chunk_lens, *, scale=None, window=None,
                                 k_scale=None, v_scale=None, q_tile=None,
                                 partials=False,
                                 interpret: bool | None = None):
    """Ragged chunk attention over block tables. q: [A, C, H, D] (chunk
    queries, already rotated); k_pool/v_pool: [N, bs, H_kv, D] with the
    chunk K/V ALREADY scattered pool-side; block_tables: [A, max_blocks]
    int32 (OOB sentinel = N on unused slots); offsets/chunk_lens: [A]
    int32 — row a's queries sit at positions offsets[a] ..
    offsets[a]+chunk_lens[a]-1 and attend over pool positions
    [0, offsets[a]+chunk_lens[a]) causally. Rows with chunk_lens == 0 are
    dead (output 0). Returns [A, C, H, D] — or, with ``partials=True``
    (context parallelism), the raw (acc [A, C, H, D] f32, m [A, C, H]
    f32, l [A, C, H] f32) triple over owned table entries only."""
    a, c, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    group = h // h_kv
    max_blocks = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    quantized = k_scale is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    cg = c * group
    if q_tile is None:
        # sublane-aligned tile; one tile unless the folded chunk is large
        q_tile = min(256, -(-cg // 8) * 8)
    n_qt = -(-cg // q_tile)
    pad = n_qt * q_tile - cg

    # fold the grouped query heads into the row axis: row t of (a, kv) is
    # query position t // group, grouped head t % group — matches the
    # (head // kv_rep) GQA convention of the decode kernel
    qf = q.reshape(a, c, h_kv, group, d).transpose(0, 2, 1, 3, 4)
    qf = qf.reshape(a * h_kv, cg, d)
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))

    tables = jnp.asarray(block_tables, jnp.int32)
    offs = jnp.asarray(offsets, jnp.int32)[:, None]
    cls = jnp.asarray(chunk_lens, jnp.int32)[:, None]

    kp = jnp.moveaxis(k_pool, 2, 0)        # [H_kv, N, bs, D]
    vp = jnp.moveaxis(v_pool, 2, 0)

    def q_index(r, qt, j, tables, offs, cls):
        return (r, qt, 0)

    def kv_index(r, qt, j, tables, offs, cls):
        a_i = r // n_kv_s
        row_len = offs[a_i, 0] + cls[a_i, 0]
        n_live = (row_len + bs - 1) // bs
        last_q = offs[a_i, 0] + (qt * q_tile + q_tile - 1) // group
        # dead trailing steps (past the causal frontier or the live
        # length) revisit the last live block: same index -> no new DMA
        hi = jnp.minimum(n_live - 1, last_q // bs)
        jl = jnp.minimum(j, jnp.maximum(hi, 0))
        return (r % n_kv_s, jnp.minimum(tables[a_i, jl], n - 1), 0, 0)

    n_kv_s = h_kv
    in_specs = [
        pl.BlockSpec((1, q_tile, d), q_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qf, kp, vp]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, bs, 1), kv_index),
                     pl.BlockSpec((1, 1, bs, 1), kv_index)]
        operands += [jnp.moveaxis(k_scale, 2, 0)[..., None],
                     jnp.moveaxis(v_scale, 2, 0)[..., None]]
    out_specs = pl.BlockSpec((1, q_tile, d), q_index)
    out_shape = jax.ShapeDtypeStruct((a * h_kv, n_qt * q_tile, d), q.dtype)
    if partials:
        out_specs = [out_specs,
                     pl.BlockSpec((1, q_tile, 128), q_index),
                     pl.BlockSpec((1, q_tile, 128), q_index)]
        out_shape = [
            jax.ShapeDtypeStruct((a * h_kv, n_qt * q_tile, d), jnp.float32),
            jax.ShapeDtypeStruct((a * h_kv, n_qt * q_tile, 128),
                                 jnp.float32),
            jax.ShapeDtypeStruct((a * h_kv, n_qt * q_tile, 128),
                                 jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(a * h_kv, n_qt, max_blocks),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((q_tile, d), jnp.float32),
            # per-folded-row running max / denom, lane-replicated (scalar
            # (x, 1) VMEM stores hit Mosaic layout restrictions)
            pltpu.VMEM((q_tile, 128), jnp.float32),
            pltpu.VMEM((q_tile, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_chunk_kernel, block_size=bs,
                               scale=scale, max_blocks=max_blocks,
                               q_tile=q_tile, group=group, n_kv=h_kv,
                               window=window, quantized=quantized,
                               partials=partials, n_pool=n)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # rows and q tiles are independent; only the kv-block axis carries
        # the online-softmax state
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL,
                                 pltpu.ARBITRARY)),
        interpret=interpret,
    )(tables, offs, cls, *operands)

    def unfold(x, last):
        x = x[:, :cg].reshape(a, h_kv, c, group, *((last,) if last else ()))
        if last:
            return x.transpose(0, 2, 1, 3, 4).reshape(a, c, h, last)
        return x.transpose(0, 2, 1, 3).reshape(a, c, h)

    if partials:
        acc, m, l = out
        return unfold(acc, d), unfold(m[..., 0], 0), unfold(l[..., 0], 0)
    return unfold(out, d)


def paged_chunk_attention_xla(q, k_pool, v_pool, block_tables, offsets,
                              chunk_lens, *, scale=None, window=None,
                              k_scale=None, v_scale=None, partials=False):
    """Gather-based reference path (CPU / fallback): materialise each
    row's whole ``max_blocks*bs`` pool view and run dense masked
    attention — exactly the pre-kernel ``llama_prefill_chunk_paged``
    inner loop, kept bit-compatible for the PT_PAGED_CHUNK=0 kill
    switch. ``partials=True`` returns the (acc, m, l) triple over owned
    table entries only (context parallelism)."""
    from paddle_tpu.ops import attention as A
    a, c, h, d = q.shape
    n, bs, h_kv, _ = k_pool.shape
    max_blocks = block_tables.shape[1]
    offsets = jnp.asarray(offsets, jnp.int32)
    chunk_lens = jnp.asarray(chunk_lens, jnp.int32)
    tbl = jnp.minimum(block_tables, n - 1)
    kg = jnp.take(k_pool, tbl, axis=0)
    vg = jnp.take(v_pool, tbl, axis=0)
    if k_scale is not None:
        kg = kg.astype(jnp.float32) * jnp.take(k_scale, tbl,
                                               axis=0)[..., None]
        vg = vg.astype(jnp.float32) * jnp.take(v_scale, tbl,
                                               axis=0)[..., None]
    kg = kg.reshape(a, max_blocks * bs, h_kv, d)
    vg = vg.reshape(a, max_blocks * bs, h_kv, d)
    pool_pos = jnp.arange(max_blocks * bs)[None, None, :]
    q_pos = (offsets[:, None]
             + jnp.arange(c, dtype=jnp.int32))[:, :, None]
    row_lens = offsets + chunk_lens
    keep = (pool_pos <= q_pos) & (pool_pos < row_lens[:, None, None])
    if window is not None:
        keep &= (q_pos - pool_pos) < window
    if partials:
        # ownership mask (cp): clamped non-owned sentinel slots must not
        # contribute — the owning shard's partial covers those positions
        keep = keep & jnp.repeat(block_tables < n, bs,
                                 axis=1)[:, None, :]   # [A, C, K]
        if h_kv != h:
            kg = jnp.repeat(kg, h // h_kv, axis=2)
            vg = jnp.repeat(vg, h // h_kv, axis=2)
        scale_ = scale if scale is not None else d ** -0.5
        s = jnp.einsum("achd,akhd->ahck", q.astype(jnp.float32),
                       kg.astype(jnp.float32)) * scale_
        km = keep[:, None].astype(bool)                # [A, 1, C, K]
        s = jnp.where(km, s, _NEG_INF)
        m = jnp.max(s, axis=-1)                        # [A, H, C]
        p = jnp.exp(s - m[..., None]) * km             # kill all-masked rows
        acc = jnp.einsum("ahck,akhd->achd", p, vg.astype(jnp.float32))
        return (acc, jnp.moveaxis(m, 1, 2),            # [A, C, H]
                jnp.moveaxis(jnp.sum(p, axis=-1), 1, 2))
    return A.xla_attention(q, kg, vg, attn_mask=keep[:, None], scale=scale)


def paged_chunk_attention(q, k_pool, v_pool, block_tables, offsets,
                          chunk_lens, *, scale=None, window=None,
                          k_scale=None, v_scale=None, partials=False,
                          interpret: bool | None = None):
    """One dispatch for the ragged chunk path. ``PT_PAGED_CHUNK``
    (read at TRACE time — flip it between engine constructions together
    with ``models.paged.clear_jit_caches``):

      unset/1     Pallas kernel on TPU, XLA gather elsewhere (default)
      0/off/xla   force the XLA gather path (kill switch)
      interpret   force the interpreted Pallas kernel (off-TPU parity)

    ``k_scale``/``v_scale`` [N, bs, H_kv] f32 mark an int8 pool —
    dequantize-on-read in every implementation. Like the decode
    dispatch, a Pallas failure downgrades the process permanently
    (cached + warned + counted, never silently retried)."""
    if k_scale is not None:
        _note_trace("chunk:int8-kv")
    if partials:
        _note_trace("chunk:partials")
    mode = os.environ.get("PT_PAGED_CHUNK", "1").strip().lower()
    if mode in ("0", "off", "xla"):
        _note_trace("chunk:xla-forced")
        return paged_chunk_attention_xla(
            q, k_pool, v_pool, block_tables, offsets, chunk_lens,
            scale=scale, window=window, k_scale=k_scale, v_scale=v_scale,
            partials=partials)
    if mode == "interpret":
        _note_trace("chunk:pallas-interpret")
        return paged_chunk_attention_pallas(
            q, k_pool, v_pool, block_tables, offsets, chunk_lens,
            scale=scale, window=window, k_scale=k_scale, v_scale=v_scale,
            partials=partials, interpret=True)
    if jax.default_backend() == "tpu" and "chunk" not in _pallas_disabled:
        try:
            out = paged_chunk_attention_pallas(
                q, k_pool, v_pool, block_tables, offsets, chunk_lens,
                scale=scale, window=window, k_scale=k_scale,
                v_scale=v_scale, partials=partials, interpret=interpret)
            _note_trace("chunk:pallas")
            return out
        except Exception as e:
            _disable_pallas("chunk", e)
    _note_trace("chunk:xla")
    return paged_chunk_attention_xla(
        q, k_pool, v_pool, block_tables, offsets, chunk_lens,
        scale=scale, window=window, k_scale=k_scale, v_scale=v_scale,
        partials=partials)
