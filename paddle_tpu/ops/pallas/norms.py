"""Pallas fused norms (ref: ``paddle/phi/kernels/fusion/fused_rms_norm`` /
``fused_layernorm``). One HBM read, fp32 accumulation on the VPU, bf16 out.
Rows are processed in (block_rows, hidden) tiles — hidden stays whole so the
reduction never leaves VMEM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rms_fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, weight, epsilon=1e-6, interpret=None):
    return _rms_fwd(x, weight, epsilon, interpret)[0]


def _rows(x):
    r = 1
    for s in x.shape[:-1]:
        r *= s
    return r


def _rms_fwd(x, weight, epsilon, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    h = x.shape[-1]
    x2 = x.reshape(_rows(x), h)
    rows = x2.shape[0]
    block = min(256, rows) if rows % min(256, rows) == 0 else rows
    out = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=epsilon),
        grid=(pl.cdiv(rows, block),),
        in_specs=[pl.BlockSpec((block, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x.dtype),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(x.shape), (x, weight)


def _rms_bwd(epsilon, interpret, res, g):
    x, weight = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    w32 = weight.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + epsilon)
    xhat = x32 * inv
    dw = jnp.sum(g32 * xhat, axis=tuple(range(x.ndim - 1)))
    gw = g32 * w32
    h = x.shape[-1]
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dw.astype(weight.dtype)


rms_norm.defvjp(lambda x, w, e, i: _rms_fwd(x, w, e, i), _rms_bwd)
