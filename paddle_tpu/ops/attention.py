"""Attention ops (ref: ``paddle/phi/kernels/fusion/flash_attn`` +
``python/paddle/nn/functional/flash_attention.py``).

Layout convention matches the reference flash_attention API: [B, S, H, D].
Dispatch order on TPU: Pallas flash kernel (paddle_tpu.ops.pallas) → fused
XLA path. The XLA path is itself MXU-friendly: two batched matmuls with a
fp32 softmax that XLA fuses into the surrounding computation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -2.3819763e38  # most-negative bf16-representable; avoids nan from -inf - -inf


def _use_pallas(q) -> bool:
    import os
    if os.environ.get("PADDLE_TPU_DISABLE_FLASH", "").lower() in ("1", "true", "yes"):
        return False  # escape hatch: force the XLA attention path
    if jax.default_backend() != "tpu":
        return False
    head_dim = q.shape[-1]
    seq = q.shape[1]
    return head_dim % 128 == 0 and seq % 128 == 0


def xla_attention(query, key, value, attn_mask=None, is_causal=False, scale=None,
                  dropout_p=0.0, training=True, rng=None, window=None,
                  kv_lens=None, alibi_slopes=None):
    """Reference-semantics attention in pure XLA. [B,S,H,D]. ``window``:
    causal sliding window (token i sees [i-window+1, i]), Mistral-style.
    ``kv_lens``: [B] valid key lengths (padded-varlen batches).
    ``alibi_slopes``: [H] or [B, H] positive slopes m — adds
    ``-m * (q_pos - k_pos)`` to the scores (this path materialises the
    bias; the Pallas kernel computes it in-tile)."""
    if window is not None and not is_causal:
        raise ValueError("window requires is_causal=True")
    b, sq, h, d = query.shape
    sk = key.shape[1]
    if kv_lens is not None:
        # [B] lengths -> [B,1,1,Sk] key-padding mask, merged with attn_mask
        pad = (jnp.arange(sk)[None, :] < jnp.asarray(kv_lens)[:, None])
        pad = pad[:, None, None, :]
        if attn_mask is None:
            attn_mask = pad
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & pad
        else:
            attn_mask = jnp.where(pad, attn_mask, _NEG_INF)
    kv_heads = key.shape[2]
    if kv_heads != h:  # GQA: repeat KV heads
        rep = h // kv_heads
        key = jnp.repeat(key, rep, axis=2)
        value = jnp.repeat(value, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    q = jnp.swapaxes(query, 1, 2)  # [B,H,S,D]
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    # query positions, shared by ALiBi and the causal/window masks: aligned
    # to the END of the key axis (KV-cache decode); with kv_lens AND
    # sq < sk (decode against a PADDED cache, flash-attn's cache_seqlens
    # form) the END is each row's valid length, so the result equals a
    # trimmed-cache solo call
    if kv_lens is not None and sq < sk:
        q_pos = (jnp.asarray(kv_lens, jnp.int32)[:, None] - sq
                 + jnp.arange(sq)[None, :])            # [B, Sq]
    else:
        q_pos = jnp.broadcast_to(jnp.arange(sq) + (sk - sq), (1, sq))
    k_pos = jnp.arange(sk)
    if alibi_slopes is not None:
        # fixed head geometry, not learned — matches the Pallas kernel's
        # zero-cotangent contract on every backend
        m_sl = jax.lax.stop_gradient(
            jnp.asarray(alibi_slopes, jnp.float32)).reshape(-1, h)  # [1|B,H]
        dist = (q_pos[:, :, None] - k_pos[None, None, :]).astype(jnp.float32)
        if not is_causal:
            dist = jnp.abs(dist)   # bidirectional ALiBi: symmetric decay
        scores = scores - m_sl[:, :, None, None] * dist[:, None]
    if is_causal or window is not None:
        keep = (q_pos[:, :, None] >= k_pos[None, None, :]) if is_causal \
            else jnp.ones((1, sq, sk), bool)
        if window is not None:
            keep &= (q_pos[:, :, None] - k_pos[None, None, :]) < window
        scores = jnp.where(keep[:, None], scores, _NEG_INF)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, _NEG_INF)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and training:
        if rng is None:
            from paddle_tpu.core.random import next_key
            rng = next_key()
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    probs = probs.astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, rng=None, scale=None,
                                 window=None, kv_lens=None, alibi_slopes=None):
    """Dispatch: Pallas flash (incl. the padded-varlen ``kv_lens`` path and
    in-tile ``alibi_slopes``) → XLA. An ARBITRARY ``attn_mask`` always
    takes the XLA path: a dense [.., Sq, Sk] mask has already materialised
    O(S^2) memory, so flash's advantage is gone — express padding as
    ``kv_lens`` and ALiBi as ``alibi_slopes`` to keep the fused kernel
    (ref: flash_attn's varlen/padded + alibi_slopes variants)."""
    h, kv = query.shape[2], key.shape[2]
    if (attn_mask is None and (dropout_p == 0.0 or not training)
            and _use_pallas(query)
            and h % kv == 0 and (window is None or is_causal)):
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention
            # GQA handled inside the kernel (kv row = q row // rep) — no
            # materialised K/V repeat
            return flash_attention(query, key, value, causal=is_causal, scale=scale,
                                   window=window, kv_lens=kv_lens,
                                   alibi_slopes=alibi_slopes)
        except Exception:
            pass
    return xla_attention(query, key, value, attn_mask=attn_mask, is_causal=is_causal,
                         scale=scale, dropout_p=dropout_p, training=training, rng=rng,
                         window=window, kv_lens=kv_lens,
                         alibi_slopes=alibi_slopes)


flash_attention = scaled_dot_product_attention


# -- rotary embedding (ref: paddle.incubate.nn.functional.fused_rotary_position_embedding)

def resolve_rope_scaling(base, head_dim, scaling, seq_len=None,
                         max_position_embeddings=None, *,
                         allow_dynamic=True, cur_len=None):
    """The ONE place the rope_scaling math lives. Returns
    ``(base, position_divisor)`` for the reference rope_scaling dict
    (PaddleNLP/HF convention):
      {"type": "linear",  "factor": f} — position interpolation (pos / f)
      {"type": "ntk",     "factor": f} — base *= f^(d/(d-2)) (fixed NTK)
      {"type": "dynamic", "factor": f} — NTK base grows once the length
        exceeds the trained window. Fixed-shape decode paths carry the
        CURRENT length as traced data via ``cur_len`` (scalar or [B]
        per-row) — the returned base is then traced (per-row: [B] or
        [B, 1]); a decode path that passes neither raises
        (``allow_dynamic=False``) instead of silently mis-rotating.
        Per-step bases match HF generation semantics: earlier cache
        entries keep the base they were rotated with.
    """
    if not scaling:
        return base, 1.0
    kind, factor = scaling["type"], float(scaling["factor"])
    if kind == "linear":
        return base, factor
    if kind == "ntk":
        return base * factor ** (head_dim / (head_dim - 2)), 1.0
    if kind == "dynamic":
        if cur_len is not None:
            trained = max_position_embeddings
            if not trained:
                raise ValueError(
                    "dynamic rope_scaling with a traced cur_len needs "
                    "max_position_embeddings (the trained window)")
            alpha = jnp.maximum(
                factor * jnp.asarray(cur_len, jnp.float32) / trained
                - (factor - 1.0), 1.0)     # <= trained: unscaled (alpha 1)
            return base * alpha ** (head_dim / (head_dim - 2)), 1.0
        if not allow_dynamic:
            raise NotImplementedError(
                "dynamic-NTK rope_scaling needs the current sequence "
                "length; pass cur_len (traced) or use 'linear'/'ntk'")
        trained = max_position_embeddings or seq_len
        if seq_len is not None and seq_len > trained:
            alpha = factor * seq_len / trained - (factor - 1)  # HF formula
            base = base * alpha ** (head_dim / (head_dim - 2))
        return base, 1.0
    raise ValueError(f"unknown rope_scaling type {kind!r}")


def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32, position_ids=None,
                 scaling=None, max_position_embeddings=None,
                 allow_dynamic=True, cur_len=None):
    """``scaling``: reference rope_scaling dict — see resolve_rope_scaling.
    ``cur_len``: traced current total length for dynamic scaling inside
    fixed-shape decode (the base becomes traced data, no recompile)."""
    base, pos_div = resolve_rope_scaling(
        base, head_dim, scaling, seq_len=seq_len,
        max_position_embeddings=max_position_embeddings,
        allow_dynamic=allow_dynamic, cur_len=cur_len)
    ar = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    pos = jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None else position_ids
    if pos_div != 1.0:
        pos = pos / pos_div
    base = jnp.asarray(base, jnp.float32)
    if base.ndim == 0:
        freqs = jnp.outer(pos, 1.0 / (base ** ar))          # [S, D/2]
    else:
        # per-ROW dynamic base (ragged lengths): [B, S, D/2]
        inv_freq = 1.0 / (base[:, None] ** ar[None, :])
        freqs = pos[None, :, None] * inv_freq[:, None, :]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin):
    """x: [B,S,H,D]; cos/sin: [S, D/2] (shared) or [B, S, D/2] (per-row
    dynamic base). NeoX-style rotate-half (LLaMA)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 3:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def apply_rope_interleaved(x, cos, sin):
    """GPT-J-style INTERLEAVED rotary: pairs are (even, odd) lanes
    ``(x[2i], x[2i+1])``, not the half-split. x: [B,S,H,D(rot)];
    cos/sin: [S, D/2]."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def fused_rotary_position_embedding(q, k, seq_len=None, base=10000.0, position_ids=None):
    s = seq_len or q.shape[1]
    cos, sin = rope_cos_sin(s, q.shape[-1], base=base, dtype=jnp.float32,
                            position_ids=position_ids)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin)


# -- fused residual chains (ref fused_bias_dropout_residual_layer_norm) -----

def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None,
                                           ln_bias=None, dropout_rate=0.0,
                                           epsilon=1e-5, training=True, rng=None):
    from paddle_tpu.nn import functional as F
    y = x if bias is None else x + bias
    y = F.dropout(y, dropout_rate, training=training, rng=rng)
    y = y + residual
    return F.layer_norm(y, y.shape[-1], ln_scale, ln_bias, epsilon)
