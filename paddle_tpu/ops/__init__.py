"""Fused/accelerated ops (ref: ``paddle/phi/kernels/fusion/`` +
``paddle.incubate.nn.functional``).

On TPU most "fusion" is XLA's job; the functions here exist to (a) provide
the reference's fused-op API surface and (b) dispatch to hand-written Pallas
kernels where XLA's default schedule leaves HBM bandwidth on the table
(flash attention, long-row RMSNorm).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import (
    apply_rope,
    flash_attention,
    fused_bias_dropout_residual_layer_norm,
    fused_rotary_position_embedding,
    rope_cos_sin,
    scaled_dot_product_attention,
    xla_attention,
)


def fused_rms_norm(x, weight=None, epsilon=1e-6):
    """Dispatch: Pallas kernel on TPU for long rows, else jnp (XLA fuses it)."""
    if jax.default_backend() == "tpu" and x.shape[-1] % 128 == 0 and x.shape[-1] >= 512:
        try:
            from paddle_tpu.ops.pallas.norms import rms_norm as pallas_rms
            return pallas_rms(x, weight, epsilon)
        except Exception:
            pass
    from paddle_tpu.nn.functional import rms_norm
    return rms_norm(x, weight, epsilon)


def fused_layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    from paddle_tpu.nn.functional import layer_norm
    return layer_norm(x, x.shape[-1], weight, bias, epsilon)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    if transpose_weight:
        weight = weight.T
    y = x @ weight
    return y if bias is None else y + bias


def fused_linear_activation(x, weight, bias=None, activation="gelu"):
    y = fused_linear(x, weight, bias)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
           "none": lambda v: v}[activation]
    return act(y)


def fused_dropout_add(x, y, p=0.0, training=True, rng=None):
    from paddle_tpu.nn.functional import dropout
    return dropout(x, p, training=training, rng=rng) + y


def swiglu(x, y=None):
    from paddle_tpu.nn.functional import swiglu as _swiglu
    return _swiglu(x, y)
