"""Audio feature extraction (ref: ``python/paddle/audio/``): mel filterbanks,
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC.

Everything composes from ``paddle_tpu.signal.stft`` + small dense matmuls,
so feature extraction jits and runs on-device (the reference runs these as
CPU ops feeding the GPU)."""
from __future__ import annotations

import math

import jax.numpy as jnp

from paddle_tpu import signal as _signal

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "create_dct", "power_to_db",
    "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
]


def hz_to_mel(freq, htk=False):
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    # Slaney formula (reference default)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(freq, 1e-10) / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    return mel_to_hz(jnp.linspace(lo, hi, n_mels), htk)


def fft_frequencies(sr, n_fft):
    return jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb = fb * enorm[:, None]
    return fb


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """[n_mels, n_mfcc] DCT-II basis."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct = dct * jnp.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].set(dct[:, 0] / math.sqrt(2.0))
    else:
        dct = dct * 2.0
    return dct


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    log_spec = 10.0 * jnp.log10(jnp.maximum(spect, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def _window(name, n):
    if n <= 1 or name in (None, "rect", "rectangular", "boxcar", "ones"):
        # scipy convention: windows of length <= 1 are [1.0]
        return jnp.ones((n,), jnp.float32)
    t = 2 * math.pi * jnp.arange(n) / n
    if name == "hann":
        return 0.5 - 0.5 * jnp.cos(t)
    if name == "hamming":
        return 0.54 - 0.46 * jnp.cos(t)
    if name == "blackman":
        return 0.42 - 0.5 * jnp.cos(t) + 0.08 * jnp.cos(2 * t)
    raise ValueError(f"unsupported window {name!r}; use hann/hamming/"
                     "blackman/rect")


def get_window(window, win_length, fftbins=True):
    """Ref paddle.audio.functional.get_window — named window of a given
    length (periodic when fftbins, matching the reference/scipy default)."""
    name = window[0] if isinstance(window, (tuple, list)) else window
    if fftbins:
        return _window(name, win_length)
    if win_length <= 1:
        return jnp.ones((win_length,), jnp.float32)
    # symmetric: same cosine series with denominator N-1, k = 0..N-1
    if name in (None, "rect", "rectangular", "boxcar", "ones"):
        return jnp.ones((win_length,), jnp.float32)
    t = 2 * math.pi * jnp.arange(win_length) / (win_length - 1)
    if name == "hann":
        return 0.5 - 0.5 * jnp.cos(t)
    if name == "hamming":
        return 0.54 - 0.46 * jnp.cos(t)
    if name == "blackman":
        return 0.42 - 0.5 * jnp.cos(t) + 0.08 * jnp.cos(2 * t)
    raise ValueError(f"unsupported window {name!r}")


class Spectrogram:
    """Ref: paddle.audio.features.Spectrogram (power spectrogram)."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect"):
        self.n_fft, self.power = n_fft, power
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.window = _window(window, self.win_length)
        self.center, self.pad_mode = center, pad_mode

    def __call__(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                            self.window, center=self.center,
                            pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram:
    """Ref: paddle.audio.features.MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney"):
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm)

    def __call__(self, x):
        spec = self.spectrogram(x)  # [..., n_freq, n_frames]
        return jnp.einsum("mf,...ft->...mt", self.fbank, spec)


class LogMelSpectrogram(MelSpectrogram):
    """Ref: paddle.audio.features.LogMelSpectrogram."""

    def __init__(self, *args, ref_value=1.0, amin=1e-10, top_db=None, **kw):
        super().__init__(*args, **kw)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def __call__(self, x):
        return power_to_db(super().__call__(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC:
    """Ref: paddle.audio.features.MFCC (log-mel → DCT-II)."""

    def __init__(self, sr=22050, n_mfcc=13, n_mels=64, **kw):
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **kw)
        self.dct = create_dct(n_mfcc, n_mels)

    def __call__(self, x):
        mel = self.logmel(x)  # [..., n_mels, n_frames]
        return jnp.einsum("mk,...mt->...kt", self.dct, mel)
