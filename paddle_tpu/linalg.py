"""Linear-algebra ops (ref: ``python/paddle/tensor/linalg.py``,
``paddle.linalg`` namespace).

Decompositions lower to XLA's native TPU implementations (QR/SVD/eigh run
on-chip; nonsymmetric ``eig`` has no TPU lowering anywhere, so it round-trips
through the host LAPACK — same behaviour the reference gets by running eig on
CPU). All functions are jit-safe except where noted.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "slogdet",
    "eig", "eigh", "eigvals", "eigvalsh", "householder_product", "inv",
    "lstsq", "lu", "lu_unpack", "matrix_exp", "matrix_power", "matrix_rank",
    "multi_dot", "norm", "pinv", "qr", "solve", "svd", "svdvals",
    "triangular_solve", "vector_norm", "matrix_norm", "dist",
]


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def cholesky_solve(x, y, upper=False):
    """Solve A @ out = x given the Cholesky factor y of A."""
    if upper:
        y = jnp.swapaxes(y, -1, -2).conj()
    z = jax.scipy.linalg.solve_triangular(y, x, lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(y, -1, -2).conj(), z, lower=False)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


def inv(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    """Ref signature: solves x @ out = y with x triangular."""
    a = jnp.swapaxes(x, -1, -2) if transpose else x
    return jax.scipy.linalg.solve_triangular(
        a, y, lower=not upper if not transpose else upper,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def _host_eig(x, compute_vectors):
    """Nonsymmetric eig has no TPU/XLA lowering — evaluate on the host.

    Eager calls go straight through numpy (works on every backend, including
    tunnelled TPUs with no host-callback support); traced calls use
    pure_callback, which requires a backend with host send/recv.
    """
    cdtype = jnp.complex64 if x.dtype in (jnp.float32, jnp.complex64) else jnp.complex128
    if not isinstance(x, jax.core.Tracer):
        a = np.asarray(jax.device_get(x))
        # keep results on the host CPU device: some TPU transports cannot
        # round-trip complex arrays, and downstream eig consumers are
        # host-side anyway
        cpu = jax.devices("cpu")[0]
        if compute_vectors:
            w, v = np.linalg.eig(a)
            return (jax.device_put(w.astype(cdtype), cpu),
                    jax.device_put(v.astype(cdtype), cpu))
        return jax.device_put(np.linalg.eigvals(a).astype(cdtype), cpu)
    if compute_vectors:
        def cb(a):
            w, v = np.linalg.eig(np.asarray(a))
            return w.astype(cdtype), v.astype(cdtype)

        shape = (jax.ShapeDtypeStruct(x.shape[:-1], cdtype),
                 jax.ShapeDtypeStruct(x.shape, cdtype))
        return jax.pure_callback(cb, shape, x, vmap_method="sequential")

    def cb(a):
        return np.linalg.eigvals(np.asarray(a)).astype(cdtype)

    return jax.pure_callback(
        cb, jax.ShapeDtypeStruct(x.shape[:-1], cdtype), x,
        vmap_method="sequential")


def eig(x):
    return _host_eig(x, compute_vectors=True)


def eigvals(x):
    return _host_eig(x, compute_vectors=False)


def lu(x, pivot=True):
    """Returns (LU, pivots) packed like the reference (1-based pivots)."""
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv + 1


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """2-D unpack of ``lu`` output into (P, L, U); batch via jax.vmap."""
    m, n = lu_data.shape[-2:]
    k = min(m, n)
    L = jnp.tril(lu_data, -1)[..., :, :k] + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data)[..., :k, :]
    piv = lu_pivots - 1  # back to 0-based swap sequence

    def body(i, perm):
        j = piv[i]
        pi, pj = perm[i], perm[j]
        return perm.at[i].set(pj).at[j].set(pi)

    perm = lax.fori_loop(0, piv.shape[0], body, jnp.arange(m))
    # rows of A permuted by perm: A = P @ L @ U with P[perm[i], i] = 1
    P = jax.nn.one_hot(perm, m, dtype=lu_data.dtype).T
    return P, L, U


def matrix_exp(x):
    return jax.scipy.linalg.expm(x)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    """Count of singular values above ``tol`` — ``tol`` is ABSOLUTE
    (reference semantics), default eps-scaled like numpy."""
    if hermitian:
        s = jnp.abs(jnp.linalg.eigvalsh(x))
    else:
        s = jnp.linalg.svd(x, compute_uv=False)
    if tol is None:
        eps = jnp.finfo(x.dtype).eps
        tol = s.max(axis=-1, keepdims=True) * max(x.shape[-2:]) * eps
    return jnp.sum(s > tol, axis=-1)


def householder_product(x, tau):
    """Q from the compact Householder form, 2-D (ref:
    paddle.linalg.householder_product); batch via jax.vmap."""
    m, n = x.shape
    Q = jnp.eye(m, dtype=x.dtype)
    for i in range(n):
        # rank-1 update Q @ (I - tau v v*) = Q - tau (Q v) v*
        v = jnp.where(jnp.arange(m) > i, x[:, i], 0.0).at[i].set(1.0)
        Q = Q - tau[i] * jnp.outer(Q @ v, v.conj())
    return Q[:, :n]


def multi_dot(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out @ x
    return out


def cond(x, p=None):
    if p is None or p == 2:
        s = svdvals(x)
        return s[..., 0] / s[..., -1]
    return norm(x, p=p, axis=(-2, -1)) * norm(inv(x), p=p, axis=(-2, -1))


def _keep_all_dims(val, ndim):
    return val.reshape((1,) * ndim)


def norm(x, p=None, axis=None, keepdim=False):
    """Unified vector/matrix norm (ref: paddle.linalg.norm)."""
    if p == "fro":
        ax = tuple(axis) if isinstance(axis, (tuple, list)) else \
            (axis,) if axis is not None else None
        return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=ax,
                                keepdims=keepdim))
    if p == "nuc":
        if axis is not None and not isinstance(axis, (tuple, list)):
            raise ValueError("nuclear norm needs a 2-axis tuple, got "
                             f"axis={axis!r}")
        ax = tuple(a % x.ndim for a in axis) if axis is not None \
            else (x.ndim - 2, x.ndim - 1)
        xm = jnp.moveaxis(x, ax, (-2, -1))
        out = jnp.sum(jnp.linalg.svd(xm, compute_uv=False), axis=-1)
        if keepdim:
            out = jnp.expand_dims(jnp.expand_dims(out, -1), -1)
            out = jnp.moveaxis(out, (-2, -1), ax)
        return out
    if isinstance(axis, (tuple, list)) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)
    if p is None:
        p = 2
    if axis is None:
        out = jnp.linalg.norm(x.reshape(-1), ord=p)
        return _keep_all_dims(out, x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def vector_norm(x, p=2, axis=None, keepdim=False):
    if axis is None:
        out = jnp.linalg.norm(x.reshape(-1), ord=p)
        return _keep_all_dims(out, x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def dist(x, y, p=2):
    return vector_norm(x - y, p=p)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def ormqr(x, tau, y, left=True, transpose=False):
    """Ref linalg.ormqr: multiply ``y`` by the implicit Q of the
    householder factors ``(x, tau)`` (geqrf layout). Reflectors are applied
    directly — k rank-1 updates, no m x m Q materialisation."""
    from jax import lax as _lax

    m, k = x.shape[-2], x.shape[-1]
    rows = jnp.arange(m)
    forward = (left and transpose) or (not left and not transpose)

    def body(step, out):
        # Q = H_0 H_1 ... H_{k-1}; iterate in the order Q (or Q^T) applies
        i = step if forward else k - 1 - step
        col = _lax.dynamic_index_in_dim(x, i, axis=-1, keepdims=False)
        v = jnp.where(rows < i, 0.0, jnp.where(rows == i, 1.0, col))
        t = _lax.dynamic_index_in_dim(tau, i, axis=-1,
                                      keepdims=False)[..., None, None]
        if left:
            proj = jnp.einsum("...m,...mn->...n", v, out)
            return out - t * v[..., :, None] * proj[..., None, :]
        proj = jnp.einsum("...nm,...m->...n", out, v)
        return out - t * proj[..., :, None] * v[..., None, :]

    # one traced body, k sequential steps — trace size O(1) in k
    return _lax.fori_loop(0, k, body, y)


def svd_lowrank(x, q=6, niter=2, M=None):
    """Ref linalg.svd_lowrank — randomized low-rank SVD (Halko et al.):
    subspace iteration with QR re-orthonormalisation; all matmul/QR, so it
    maps straight onto the MXU. Deterministic under the global seed."""
    from paddle_tpu.core.random import next_key
    if M is not None:
        x = x - M
    m, n = x.shape[-2], x.shape[-1]
    k = min(q, m, n)
    g = jax.random.normal(next_key(), x.shape[:-2] + (n, k), jnp.float32)
    y = x @ g
    qmat, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        z = jnp.swapaxes(x, -1, -2) @ qmat
        z, _ = jnp.linalg.qr(z)
        y = x @ z
        qmat, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(qmat, -1, -2) @ x
    u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u_b, s, jnp.swapaxes(vh, -1, -2)
