"""``paddle.static`` compatibility surface (ref: ``python/paddle/static/``).

The reference's static graph (Program/Executor/scope) is subsumed by XLA:
``jax.jit`` IS the static graph (SURVEY.md §2.10). This module keeps the
entry points users actually touch — InputSpec, save/load_inference_model —
and routes them to the jit/export machinery so static-mode scripts port
without rewrites. Program/Executor-level APIs raise with a pointer to the
TPU-native equivalent rather than silently no-op.
"""
from __future__ import annotations

from paddle_tpu.jit import InputSpec, load as _jit_load, save as _jit_save

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "Executor", "default_main_program"]


def save_inference_model(path_prefix, feed_vars, fetch_vars=None, executor=None,
                         program=None, model=None, **kw):
    """Ref ``paddle.static.save_inference_model``. Here: export the model (or
    jittable fn) with the feed specs to a StableHLO artifact."""
    target = model if model is not None else fetch_vars
    if target is None or isinstance(target, (list, tuple)):
        raise ValueError(
            "save_inference_model: pass the Module/function as `model=` (the "
            "Program/Executor form has no equivalent — jit.save exports the "
            "traced computation directly)")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    return _jit_save(target, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix, executor=None, **kw):
    """Ref ``paddle.static.load_inference_model`` → a callable program."""
    return _jit_load(path_prefix)


class _Removed:
    _msg = ("paddle.static Program/Executor do not exist in paddle_tpu: "
            "jax.jit is the graph mode. Use paddle_tpu.jit / jit.save / "
            "jit.load (SURVEY.md §2.10).")

    def __init__(self, *a, **kw):
        raise NotImplementedError(self._msg)


class Program(_Removed):
    pass


class Executor(_Removed):
    pass


def default_main_program():
    raise NotImplementedError(_Removed._msg)
