"""DLPack interchange (ref: ``python/paddle/utils/dlpack.py``).

Zero-copy tensor exchange with other frameworks on the same host. JAX
arrays implement the DLPack protocol natively; these wrappers keep the
reference's entry-point names.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def to_dlpack(x):
    """Export a paddle_tpu (jax) array as a DLPack capsule."""
    x = jnp.asarray(x)
    return x.__dlpack__()


def from_dlpack(capsule_or_tensor):
    """Import from a DLPack capsule or any object with ``__dlpack__``
    (torch tensor, numpy array, ...). Device placement follows the
    producer; TPU-backed consumers should ``jax.device_put`` after."""
    return jax.dlpack.from_dlpack(capsule_or_tensor)
