"""Deterministic fault injection (chaos layer).

The ROADMAP north-star is a production system; PAPER.md §2.9 promises
EXACT elastic restore. The only way to *prove* the recovery paths
(preemption, NaN skip, elastic restart, crash-safe checkpoints) stay
correct is to drive them through induced failures on demand — seeded
and reproducible, so a chaos test that fails once fails every time.

Instrumented sites call :func:`fault_point` (a cheap no-op while no
rule is installed). Tests install rules against site names:

    serving.alloc    block allocation inside the engine (MemoryError)
    serving.tick     top of ``LLMEngine.step`` (exception / stall)
    serving.preempt  induced preemption (rule action receives the engine)
    serving.spec_verify  before the speculative verify forward — an
                     exception aborts the spec round exception-atomically
                     and the tick falls back to one-token decode
    serving.moe_dispatch  before the decode tick of an MoE model (the
                     expert all_to_all — a dead expert shard); an
                     exception aborts the tick exception-atomically:
                     no blocks leak and ``assert_quiescent`` stays clean
    router.dispatch  before a request is handed to a replica engine —
                     fires pre-add, so the request stays with the router
                     (requeued, re-dispatched next step)
    router.kv_transfer  before a prefilled sequence is extracted for the
                     prefill→decode handoff; exception-atomic — the
                     sequence is pulled back and requeued, no blocks leak
                     on either replica
    router.replica_death  before a replica's step — an exception marks
                     the replica dead; its live requests requeue to a
                     healthy replica exactly once
    serving.prefix_evict  before a radix prefix-cache leaf eviction
                     frees its parked block — fires pre-mutation, so an
                     exception leaves the trie and free list untouched
                     (the allocation that triggered it fails cleanly)
    serving.adapter_swap  before a host→device LoRA adapter upload into
                     the stacked device cache (AdapterStore.ensure) —
                     fires pre-mutation, so an exception leaves the
                     cache, pins, and free list untouched; the scheduler
                     defers that admission to a later tick (no leaked
                     device cache entries, ``assert_quiescent`` clean)
    train.step       top of each trainer step (exception / stall)
    train.loss       loss override — return value replaces the real loss
                     (NaN injection)
    ckpt.write       before the checkpoint tmp file is written (OSError)
    ckpt.rename      between tmp-write and the atomic rename — the
                     crash window (InjectedCrash)
    collective.all_reduce  before a mesh all_reduce (dead-link chaos)
    router.kv_stall  straggler window inside one prefill→decode handoff
                     attempt — fires before ``KVTransfer.ship``; a
                     ``delay_s`` rule here makes the transfer slow (which
                     trips the hedging deadline), an exception makes it
                     fail (which burns one retry attempt)
    router.kv_partial  after ship, before install — a rule action
                     receives the shipped payload and returns a
                     corrupted/truncated replacement; geometry+checksum
                     validation must reject it and the router retries
                     from the pristine source payload
    serving.snapshot  before a host-side session snapshot is captured —
                     an exception skips this capture (the router keeps
                     the previous, staler snapshot)

Rules fire on specific hit counts of their site (``on={3, 5}``), every
k-th hit (``every=3``), or a seeded pseudo-random schedule
(:meth:`FaultRegistry.schedule`). An exhausted rule (``times``) stops
firing; ``clear()`` removes everything. All state is per-process and
host-side only — nothing here ever traces into a jitted program.

Delay faults: ``delay_s`` sleeps *before* the rule's other behaviour
(exc/action) and composes with it — a rule with only ``delay_s`` models
a slow-but-correct straggler, ``delay_s`` + ``exc`` a slow failure. The
sleep goes through the swappable ``FAULTS.sleep`` so tests can fake
time. (``stall_s`` is the older exclusive form and always uses real
``time.sleep``.) The full machine-readable site list lives in
:data:`SITES`; ``tests/test_faults.py`` cross-checks it against the
``fault_point``/``fault_value`` call sites in the source tree.

Usage::

    from paddle_tpu.utils.faults import FAULTS, InjectedFault
    with FAULTS.scope("serving.alloc", exc=MemoryError, on={2, 3}):
        eng.run()        # 2nd and 3rd allocation attempts fail

Ref: Fleet's elastic controller is validated the same way in the
reference — induced pod kills, not production incidents.
"""
from __future__ import annotations

import contextlib
import random
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from paddle_tpu.observability import METRICS, instant as _trace_instant
from paddle_tpu.observability.flight import FLIGHT

# chaos runs are self-describing: every firing increments this counter
# (labelled by site) and drops an instant event on the trace timeline
_INJECTED = METRICS.counter(
    "faults_injected_total",
    "fault-injection firings by chaos site", labelnames=("site",))

__all__ = ["FAULTS", "FaultRegistry", "FaultRule", "InjectedFault",
           "InjectedCrash", "SITES", "fault_point", "fault_value"]

# Every instrumented chaos site in the tree, site → one-line contract.
# tests/test_faults.py asserts this stays in sync with the actual
# fault_point()/fault_value() call sites, so a new site cannot land
# without documenting what an injected failure there must guarantee.
SITES = {
    "serving.alloc": "block allocation inside the engine (MemoryError)",
    "serving.tick": "top of LLMEngine.step (exception / stall)",
    "serving.preempt": "induced preemption (action receives the engine)",
    "serving.spec_verify": "before the speculative verify forward; "
                           "exception-atomic spec-round abort",
    "serving.moe_dispatch": "before an MoE decode tick's expert "
                            "all_to_all; exception-atomic tick abort",
    "serving.kv_quant": "before an int8 pool's quantize-on-write scatter; "
                        "exception-atomic tick abort, no stale scales",
    "serving.cp_gather": "before a cp>1 decode tick's cross-shard partial "
                         "gather; exception-atomic tick abort, no leaked "
                         "blocks, ledger reconciles",
    "serving.prefix_evict": "before a radix prefix-cache leaf eviction; "
                            "pre-mutation, trie/free list untouched",
    "serving.adapter_swap": "before a LoRA adapter host→device upload; "
                            "pre-mutation, admission deferred",
    "serving.snapshot": "before a session-durability snapshot capture; "
                        "exception skips it, stale snapshot kept",
    "router.dispatch": "before a request is handed to a replica engine; "
                       "pre-add, request stays with the router",
    "router.kv_transfer": "before a prefilled sequence is extracted for "
                          "handoff; exception-atomic pull-back + requeue",
    "router.kv_stall": "straggler window inside one handoff ship attempt "
                       "(delay_s = slow, exc = burns a retry)",
    "router.kv_partial": "action corrupts/truncates the shipped payload; "
                         "validation rejects, router retries pristine",
    "router.replica_death": "before a replica's step; exception marks it "
                            "dead, live requests requeue exactly once",
    "collective.all_reduce": "before a mesh all_reduce (dead link)",
    "train.step": "top of each trainer step (exception / stall)",
    "train.loss": "loss override — action return replaces the loss",
    "ckpt.write": "before the checkpoint tmp file is written (OSError)",
    "ckpt.rename": "between tmp-write and atomic rename (InjectedCrash)",
}


class InjectedFault(RuntimeError):
    """Default exception raised by a rule with no explicit ``exc``."""


class InjectedCrash(RuntimeError):
    """Simulates a process kill at a crash window (e.g. mid-checkpoint-
    save). A RuntimeError so ElasticRunner's restart net catches it."""


@dataclass
class FaultRule:
    """One installed fault. Matches when its site is hit and the hit
    index (0-based, per site, counted from installation) satisfies
    ``on``/``every``; fires at most ``times`` times (None = unbounded).

    Exactly one primary behaviour:
      * ``exc``     — an exception class or instance to raise
      * ``action``  — called with the site's context kwargs; its return
                      value is handed back to the fault point (the
                      ``train.loss`` site uses it as the loss override)
      * ``stall_s`` — sleep this long (legacy exclusive stall injection)

    ``delay_s`` is orthogonal and composes: it sleeps (through the
    registry's swappable ``sleep``) *before* the primary behaviour runs;
    a rule with only ``delay_s`` is a pure straggler — slow, not broken.
    """
    site: str
    on: Optional[frozenset] = None
    every: Optional[int] = None
    times: Optional[int] = None
    exc: Any = None
    action: Optional[Callable[..., Any]] = None
    stall_s: Optional[float] = None
    delay_s: Optional[float] = None
    fired: int = 0
    _base_hit: int = 0          # site hit count when the rule was installed

    def matches(self, hit: int) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        rel = hit - self._base_hit
        if self.on is not None:
            return rel in self.on
        if self.every is not None:
            return self.every > 0 and rel % self.every == self.every - 1
        return True

    def fire(self, ctx: dict, sleep: Callable[[float], None] = time.sleep):
        self.fired += 1
        if self.delay_s is not None:
            sleep(self.delay_s)
        if self.exc is not None:
            raise self.exc if isinstance(self.exc, BaseException) \
                else self.exc(f"injected fault at {self.site}")
        if self.stall_s is not None:
            time.sleep(self.stall_s)
            return None
        if self.action is not None:
            return self.action(ctx)
        if self.delay_s is not None:
            return None          # pure delay fault: slow, not broken
        raise InjectedFault(f"injected fault at {self.site}")


class FaultRegistry:
    """Per-process rule table + per-site hit counters. The module-level
    :data:`FAULTS` singleton is what the instrumented sites consult."""

    def __init__(self):
        self._rules: dict[str, list[FaultRule]] = defaultdict(list)
        self.hits: dict[str, int] = defaultdict(int)
        self.log: list[tuple[str, int]] = []   # (site, hit) of every firing
        self.sleep: Callable[[float], None] = time.sleep  # delay_s clock

    # ------------------------------------------------------------- admin
    def install(self, site: str, *, on=None, every: Optional[int] = None,
                times: Optional[int] = None, exc=None,
                action: Optional[Callable] = None,
                stall_s: Optional[float] = None,
                delay_s: Optional[float] = None) -> FaultRule:
        rule = FaultRule(site=site,
                         on=None if on is None else frozenset(on),
                         every=every, times=times, exc=exc, action=action,
                         stall_s=stall_s, delay_s=delay_s,
                         _base_hit=self.hits[site])
        self._rules[site].append(rule)
        return rule

    def schedule(self, site: str, *, seed: int, p: float, horizon: int,
                 **kw) -> FaultRule:
        """Seeded pseudo-random hit set: each of the next ``horizon``
        hits of ``site`` fails independently with probability ``p``,
        drawn from ``random.Random(seed)`` — the same seed always yields
        the same schedule, so chaos runs are reproducible bit-for-bit."""
        rng = random.Random(seed)
        on = frozenset(i for i in range(horizon) if rng.random() < p)
        return self.install(site, on=on, **kw)

    def remove(self, rule: FaultRule):
        self._rules.get(rule.site, []) and self._rules[rule.site].remove(rule)
        if not self._rules.get(rule.site):
            self._rules.pop(rule.site, None)

    def clear(self, site: Optional[str] = None):
        if site is None:
            self._rules.clear()
            self.hits.clear()
            self.log.clear()
            self.sleep = time.sleep   # drop any test-injected fake clock
        else:
            self._rules.pop(site, None)

    def active(self) -> bool:
        return bool(self._rules)

    @contextlib.contextmanager
    def scope(self, site: str, **kw):
        """Install a rule for the duration of a with-block."""
        rule = self.install(site, **kw)
        try:
            yield rule
        finally:
            self.remove(rule)

    # ------------------------------------------------------------ firing
    def fire(self, site: str, **ctx):
        """Advance ``site``'s hit counter; run every matching rule.
        Returns the last matching rule's action result (None when no
        rule matched or the rule raised/stalled)."""
        hit = self.hits[site]
        self.hits[site] = hit + 1
        out = None
        for rule in self._rules.get(site, ()):
            if rule.matches(hit):
                self.log.append((site, hit))
                _INJECTED.inc(site=site)
                _trace_instant(f"fault:{site}", hit=hit)
                FLIGHT.record("fault", site=site, hit=hit)
                out = rule.fire(ctx, self.sleep)
        return out


FAULTS = FaultRegistry()


def fault_point(site: str, **ctx):
    """Instrumentation hook. A no-op (one dict lookup) unless a rule is
    installed for any site; returns the matched rule's action result."""
    if not FAULTS._rules:
        return None
    return FAULTS.fire(site, **ctx)


def fault_value(site: str, default, **ctx):
    """Value-override hook (e.g. ``train.loss``): returns ``default``
    unless a matching rule's action supplies a replacement."""
    if not FAULTS._rules:
        return default
    out = FAULTS.fire(site, default=default, **ctx)
    return default if out is None else out
