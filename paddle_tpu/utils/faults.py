"""Deterministic fault injection (chaos layer).

The ROADMAP north-star is a production system; PAPER.md §2.9 promises
EXACT elastic restore. The only way to *prove* the recovery paths
(preemption, NaN skip, elastic restart, crash-safe checkpoints) stay
correct is to drive them through induced failures on demand — seeded
and reproducible, so a chaos test that fails once fails every time.

Instrumented sites call :func:`fault_point` (a cheap no-op while no
rule is installed). Tests install rules against site names:

    serving.alloc    block allocation inside the engine (MemoryError)
    serving.tick     top of ``LLMEngine.step`` (exception / stall)
    serving.preempt  induced preemption (rule action receives the engine)
    serving.spec_verify  before the speculative verify forward — an
                     exception aborts the spec round exception-atomically
                     and the tick falls back to one-token decode
    serving.moe_dispatch  before the decode tick of an MoE model (the
                     expert all_to_all — a dead expert shard); an
                     exception aborts the tick exception-atomically:
                     no blocks leak and ``assert_quiescent`` stays clean
    router.dispatch  before a request is handed to a replica engine —
                     fires pre-add, so the request stays with the router
                     (requeued, re-dispatched next step)
    router.kv_transfer  before a prefilled sequence is extracted for the
                     prefill→decode handoff; exception-atomic — the
                     sequence is pulled back and requeued, no blocks leak
                     on either replica
    router.replica_death  before a replica's step — an exception marks
                     the replica dead; its live requests requeue to a
                     healthy replica exactly once
    serving.prefix_evict  before a radix prefix-cache leaf eviction
                     frees its parked block — fires pre-mutation, so an
                     exception leaves the trie and free list untouched
                     (the allocation that triggered it fails cleanly)
    serving.adapter_swap  before a host→device LoRA adapter upload into
                     the stacked device cache (AdapterStore.ensure) —
                     fires pre-mutation, so an exception leaves the
                     cache, pins, and free list untouched; the scheduler
                     defers that admission to a later tick (no leaked
                     device cache entries, ``assert_quiescent`` clean)
    train.step       top of each trainer step (exception / stall)
    train.loss       loss override — return value replaces the real loss
                     (NaN injection)
    ckpt.write       before the checkpoint tmp file is written (OSError)
    ckpt.rename      between tmp-write and the atomic rename — the
                     crash window (InjectedCrash)

Rules fire on specific hit counts of their site (``on={3, 5}``), every
k-th hit (``every=3``), or a seeded pseudo-random schedule
(:meth:`FaultRegistry.schedule`). An exhausted rule (``times``) stops
firing; ``clear()`` removes everything. All state is per-process and
host-side only — nothing here ever traces into a jitted program.

Usage::

    from paddle_tpu.utils.faults import FAULTS, InjectedFault
    with FAULTS.scope("serving.alloc", exc=MemoryError, on={2, 3}):
        eng.run()        # 2nd and 3rd allocation attempts fail

Ref: Fleet's elastic controller is validated the same way in the
reference — induced pod kills, not production incidents.
"""
from __future__ import annotations

import contextlib
import random
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from paddle_tpu.observability import METRICS, instant as _trace_instant
from paddle_tpu.observability.flight import FLIGHT

# chaos runs are self-describing: every firing increments this counter
# (labelled by site) and drops an instant event on the trace timeline
_INJECTED = METRICS.counter(
    "faults_injected_total",
    "fault-injection firings by chaos site", labelnames=("site",))

__all__ = ["FAULTS", "FaultRegistry", "FaultRule", "InjectedFault",
           "InjectedCrash", "fault_point", "fault_value"]


class InjectedFault(RuntimeError):
    """Default exception raised by a rule with no explicit ``exc``."""


class InjectedCrash(RuntimeError):
    """Simulates a process kill at a crash window (e.g. mid-checkpoint-
    save). A RuntimeError so ElasticRunner's restart net catches it."""


@dataclass
class FaultRule:
    """One installed fault. Matches when its site is hit and the hit
    index (0-based, per site, counted from installation) satisfies
    ``on``/``every``; fires at most ``times`` times (None = unbounded).

    Exactly one behaviour:
      * ``exc``     — an exception class or instance to raise
      * ``action``  — called with the site's context kwargs; its return
                      value is handed back to the fault point (the
                      ``train.loss`` site uses it as the loss override)
      * ``stall_s`` — sleep this long (stall injection)
    """
    site: str
    on: Optional[frozenset] = None
    every: Optional[int] = None
    times: Optional[int] = None
    exc: Any = None
    action: Optional[Callable[..., Any]] = None
    stall_s: Optional[float] = None
    fired: int = 0
    _base_hit: int = 0          # site hit count when the rule was installed

    def matches(self, hit: int) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        rel = hit - self._base_hit
        if self.on is not None:
            return rel in self.on
        if self.every is not None:
            return self.every > 0 and rel % self.every == self.every - 1
        return True

    def fire(self, ctx: dict):
        self.fired += 1
        if self.exc is not None:
            raise self.exc if isinstance(self.exc, BaseException) \
                else self.exc(f"injected fault at {self.site}")
        if self.stall_s is not None:
            time.sleep(self.stall_s)
            return None
        if self.action is not None:
            return self.action(ctx)
        raise InjectedFault(f"injected fault at {self.site}")


class FaultRegistry:
    """Per-process rule table + per-site hit counters. The module-level
    :data:`FAULTS` singleton is what the instrumented sites consult."""

    def __init__(self):
        self._rules: dict[str, list[FaultRule]] = defaultdict(list)
        self.hits: dict[str, int] = defaultdict(int)
        self.log: list[tuple[str, int]] = []   # (site, hit) of every firing

    # ------------------------------------------------------------- admin
    def install(self, site: str, *, on=None, every: Optional[int] = None,
                times: Optional[int] = None, exc=None,
                action: Optional[Callable] = None,
                stall_s: Optional[float] = None) -> FaultRule:
        rule = FaultRule(site=site,
                         on=None if on is None else frozenset(on),
                         every=every, times=times, exc=exc, action=action,
                         stall_s=stall_s, _base_hit=self.hits[site])
        self._rules[site].append(rule)
        return rule

    def schedule(self, site: str, *, seed: int, p: float, horizon: int,
                 **kw) -> FaultRule:
        """Seeded pseudo-random hit set: each of the next ``horizon``
        hits of ``site`` fails independently with probability ``p``,
        drawn from ``random.Random(seed)`` — the same seed always yields
        the same schedule, so chaos runs are reproducible bit-for-bit."""
        rng = random.Random(seed)
        on = frozenset(i for i in range(horizon) if rng.random() < p)
        return self.install(site, on=on, **kw)

    def remove(self, rule: FaultRule):
        self._rules.get(rule.site, []) and self._rules[rule.site].remove(rule)
        if not self._rules.get(rule.site):
            self._rules.pop(rule.site, None)

    def clear(self, site: Optional[str] = None):
        if site is None:
            self._rules.clear()
            self.hits.clear()
            self.log.clear()
        else:
            self._rules.pop(site, None)

    def active(self) -> bool:
        return bool(self._rules)

    @contextlib.contextmanager
    def scope(self, site: str, **kw):
        """Install a rule for the duration of a with-block."""
        rule = self.install(site, **kw)
        try:
            yield rule
        finally:
            self.remove(rule)

    # ------------------------------------------------------------ firing
    def fire(self, site: str, **ctx):
        """Advance ``site``'s hit counter; run every matching rule.
        Returns the last matching rule's action result (None when no
        rule matched or the rule raised/stalled)."""
        hit = self.hits[site]
        self.hits[site] = hit + 1
        out = None
        for rule in self._rules.get(site, ()):
            if rule.matches(hit):
                self.log.append((site, hit))
                _INJECTED.inc(site=site)
                _trace_instant(f"fault:{site}", hit=hit)
                FLIGHT.record("fault", site=site, hit=hit)
                out = rule.fire(ctx)
        return out


FAULTS = FaultRegistry()


def fault_point(site: str, **ctx):
    """Instrumentation hook. A no-op (one dict lookup) unless a rule is
    installed for any site; returns the matched rule's action result."""
    if not FAULTS._rules:
        return None
    return FAULTS.fire(site, **ctx)


def fault_value(site: str, default, **ctx):
    """Value-override hook (e.g. ``train.loss``): returns ``default``
    unless a matching rule's action supplies a replacement."""
    if not FAULTS._rules:
        return default
    out = FAULTS.fire(site, default=default, **ctx)
    return default if out is None else out
