from paddle_tpu.utils.profiler import (
    Profiler,
    StepTimer,
    device_memory_stats,
    dump_cost_analysis,
    record_event,
)
from paddle_tpu.utils.watchdog import StallWatchdog, WatchdogTrip, check_finite
from paddle_tpu.utils.faults import (FAULTS, FaultRegistry, InjectedCrash,
                                     InjectedFault, fault_point, fault_value)
from paddle_tpu.utils import dlpack
from paddle_tpu.utils import cpp_extension
