"""Custom C++ operators (ref capability: ``python/paddle/utils/cpp_extension/``
— CppExtension / load, the reference's compile-your-own-op story).

TPU-native split of responsibilities:
  * DEVICE compute belongs in Pallas (see ``paddle_tpu/ops/pallas``) — a
    C++ kernel cannot run on a TPU core.
  * HOST-side custom ops (the reference's CPU custom-op path: lookups,
    tokenization, custom samplers, legacy C++ math) compile here with
    ``g++`` and enter jitted programs through ``jax.pure_callback``, so a
    compiled step can call into native code at trace-defined points.

C ABI convention (documented to extension authors):
    extern "C" void <name>(const float** ins, const long long* sizes,
                           int n_ins, float* out, long long out_size);
Inputs arrive as contiguous fp32 buffers with their element counts; the
output buffer is pre-allocated by the caller from ``out_shape``. A
gradient op named ``<name>_grad`` with the same ABI (inputs = primal
inputs + upstream cotangent, output = cotangent of input 0) is wired into
a ``jax.custom_vjp`` automatically when present; additional inputs get
their own symbols ``<name>_grad1``, ``<name>_grad2``, ... (same ABI,
output shaped like input i). Inputs WITHOUT a grad symbol are
NaN-poisoned in the backward pass, so differentiating w.r.t. them fails
loudly instead of silently producing zeros.
"""
from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
import tempfile
from types import SimpleNamespace

import numpy as np


def _ghost_call(gfn, out_shape, *arrays):
    return _call(gfn, arrays, out_shape)


def _compile(sources, name, extra_cflags=None, build_directory=None,
             verbose=False):
    build = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build, exist_ok=True)
    srcs = []
    for s in sources:
        if os.path.exists(s):
            srcs.append(os.path.abspath(s))
        else:  # inline source string
            digest = hashlib.sha1(s.encode()).hexdigest()[:12]
            path = os.path.join(build, f"{name}_{digest}.cpp")
            with open(path, "w") as f:
                f.write(s)
            srcs.append(path)
    tag = hashlib.sha1((name + "|" + "|".join(extra_cflags or []) + "|"
                        + "".join(open(s).read() for s in srcs))
                       .encode()).hexdigest()[:12]
    lib_path = os.path.join(build, f"lib{name}_{tag}.so")
    if not os.path.exists(lib_path):
        # build to a process-unique temp path, then atomically rename:
        # concurrent loads (test workers, multi-host launch) never dlopen a
        # half-written .so
        tmp_path = f"{lib_path}.{os.getpid()}.tmp"
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
               + (extra_cflags or []) + srcs + ["-o", tmp_path])
        if verbose:
            print(" ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=not verbose)
        except subprocess.CalledProcessError as e:
            err = (e.stderr or b"").decode(errors="replace")
            try:
                os.unlink(tmp_path)  # don't leak half-written artifacts
            except FileNotFoundError:
                pass
            raise RuntimeError(
                f"g++ failed for extension {name!r}:\n{err}") from None
        os.replace(tmp_path, lib_path)
    return lib_path


def _bind(lib, fname):
    fn = getattr(lib, fname)
    fn.restype = None
    fn.argtypes = [ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                   ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
                   ctypes.POINTER(ctypes.c_float), ctypes.c_longlong]
    return fn


def _call(cfn, arrays, out_shape):
    arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
    ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrays])
    sizes = (ctypes.c_longlong * len(arrays))(*[a.size for a in arrays])
    out = np.empty(out_shape, np.float32)
    cfn(ptrs, sizes, len(arrays),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size)
    return out


def load(name, sources, functions, extra_cflags=None, build_directory=None,
         verbose=False):
    """Compile ``sources`` (paths or inline strings) and expose ``functions``.

    ``functions``: dict op_name -> out_shape_fn(*input_shapes) (or None for
    same-shape-as-first-input). Returns a namespace of jit-compatible
    callables; ops with an exported ``<name>_grad`` sibling get a VJP.

    Differentiation contract: the ``_grad`` ABI produces the cotangent of
    the FIRST input only — remaining inputs are treated as constants
    (zero cotangent, like ``stop_gradient``); a warning records this at
    load time so a silently-unused gradient is traceable.
    """
    import jax
    import jax.numpy as jnp

    lib_path = _compile(sources, name, extra_cflags, build_directory, verbose)
    lib = ctypes.CDLL(lib_path)
    ops = {}
    for fname, out_shape_fn in functions.items():
        cfn = _bind(lib, fname)
        shape_of = out_shape_fn or (lambda *shapes: shapes[0])

        def make(cfn=cfn, shape_of=shape_of, fname=fname):
            def host(*arrays):
                return _call(cfn, arrays,
                             shape_of(*[a.shape for a in arrays]))

            def op(*args):
                out_shape = shape_of(*[jnp.shape(a) for a in args])
                return jax.pure_callback(
                    host, jax.ShapeDtypeStruct(tuple(out_shape), jnp.float32),
                    *args, vmap_method="sequential")

            grad_name = fname + "_grad"
            if hasattr(lib, grad_name):
                # Multi-input ABI: `<name>_grad` yields input 0's cotangent;
                # optional `<name>_grad1`, `<name>_grad2`, ... yield inputs
                # 1, 2, ... Each receives (primal inputs..., g) and writes a
                # buffer shaped like ITS input. Inputs without a grad symbol
                # are non-differentiable: their cotangent is a loud NaN fill
                # so a grad taken w.r.t. them can never be silently wrong
                # (r1 advice: zeros masked missing-gradient bugs).
                gfns = {0: _bind(lib, grad_name)}
                i = 1
                while hasattr(lib, f"{grad_name}{i}"):
                    gfns[i] = _bind(lib, f"{grad_name}{i}")
                    i += 1
                import warnings
                warnings.warn(
                    f"custom op {fname!r}: gradients defined for input(s) "
                    f"{sorted(gfns)} (symbols {grad_name}<i>); any OTHER "
                    "input's cotangent is NaN-poisoned — differentiating "
                    "w.r.t. it fails loudly instead of silently yielding "
                    "zeros", stacklevel=2)

                @jax.custom_vjp
                def op_vjp(*args):
                    return op(*args)

                def fwd(*args):
                    return op(*args), args

                def bwd(res, g):
                    outs = []
                    for idx, r in enumerate(res):
                        if idx in gfns:
                            gi = jax.pure_callback(
                                functools.partial(
                                    _ghost_call, gfns[idx], jnp.shape(r)),
                                jax.ShapeDtypeStruct(jnp.shape(r),
                                                     jnp.float32),
                                *res, g, vmap_method="sequential")
                        else:
                            gi = jnp.full(jnp.shape(r), jnp.nan, jnp.float32)
                        outs.append(gi)
                    return tuple(outs)

                op_vjp.defvjp(fwd, bwd)
                return op_vjp
            return op

        ops[fname] = make()
    return SimpleNamespace(_lib_path=lib_path, **ops)


class CppExtension:
    """Ref cpp_extension.CppExtension — a (name, sources) build spec for
    ``setup``/``load``. Kept as a thin record; ``load`` does the work."""

    def __init__(self, sources, name=None, extra_compile_args=None, **kw):
        self.sources = sources
        self.name = name
        self.extra_compile_args = extra_compile_args or []


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is CUDA-only; on TPU write device kernels in Pallas "
        "(paddle_tpu/ops/pallas) and host ops via CppExtension/load")
