"""Collective-order lint (SURVEY §5: race/deadlock detection aux subsystem).

The reference detects NCCL hangs at runtime (Fleet elastic watchdog,
``paddle/fluid/distributed/collective/``). A functional SPMD program can be
checked STATICALLY instead: the classic deadlock is a collective inside
divergent control flow — one branch of a ``cond`` issues a ``psum`` the
other doesn't, or a ``while_loop`` cond-fn launches collectives — so we walk
the jaxpr and flag those patterns before anything runs on hardware.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.extend as jex

# primitive names that lower to XLA collectives
_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pbroadcast", "axis_index", "pgather",
}


@dataclass
class CollectiveIssue:
    kind: str       # "cond-divergence" | "while-cond-collective"
    detail: str

    def __str__(self):
        return f"[{self.kind}] {self.detail}"


@dataclass
class CollectiveReport:
    sequence: list = field(default_factory=list)  # ordered (prim, axes) pairs
    issues: list = field(default_factory=list)

    @property
    def ok(self):
        return not self.issues


def _axes_of(eqn) -> Any:
    for key in ("axis_name", "axes", "axis_index_groups"):
        if key in eqn.params and eqn.params[key] is not None:
            return eqn.params[key]
    return None


def _sub_jaxprs(eqn):
    out = []
    for k, v in eqn.params.items():
        if isinstance(v, jex.core.ClosedJaxpr):
            out.append((k, v.jaxpr))
        elif isinstance(v, jex.core.Jaxpr):
            out.append((k, v))
        elif isinstance(v, (tuple, list)):
            for i, item in enumerate(v):
                if isinstance(item, jex.core.ClosedJaxpr):
                    out.append((f"{k}[{i}]", item.jaxpr))
                elif isinstance(item, jex.core.Jaxpr):
                    out.append((f"{k}[{i}]", item))
    return out


def _walk(jaxpr, report: CollectiveReport, path: str = ""):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _COLLECTIVE_PRIMS and name != "axis_index":
            report.sequence.append((name, _axes_of(eqn)))
        subs = _sub_jaxprs(eqn)
        if name == "cond":
            # each branch must issue the SAME collective sequence
            branch_seqs = []
            for label, sub in subs:
                r = CollectiveReport()
                _walk(sub, r, f"{path}/{name}.{label}")
                branch_seqs.append((label, r))
            seqs = [tuple(r.sequence) for _, r in branch_seqs]
            if len(set(seqs)) > 1:
                report.issues.append(CollectiveIssue(
                    "cond-divergence",
                    f"at {path or '<root>'}: cond branches issue different "
                    f"collective sequences {dict((l, r.sequence) for l, r in branch_seqs)}"
                    " — divergent collectives deadlock SPMD programs"))
            for _, r in branch_seqs:
                report.issues.extend(r.issues)
            if seqs:
                report.sequence.extend(seqs[0])
        elif name == "while":
            for label, sub in subs:
                r = CollectiveReport()
                _walk(sub, r, f"{path}/{name}.{label}")
                if "cond" in label and r.sequence:
                    report.issues.append(CollectiveIssue(
                        "while-cond-collective",
                        f"at {path or '<root>'}: while_loop condition issues "
                        f"collectives {r.sequence} — the loop predicate must "
                        "be replicated, not collective-dependent"))
                report.sequence.extend(r.sequence)
                report.issues.extend(r.issues)
        else:
            for label, sub in subs:
                _walk(sub, report, f"{path}/{name}.{label}")


def lint_collectives(fn, *args, axis_env=None, **kwargs) -> CollectiveReport:
    """Trace ``fn`` and statically lint its collective usage.

    Use on the function you pass to ``shard_map``, with ``axis_env`` naming
    the mesh axes it runs under, e.g.
    ``lint_collectives(stage_fn, x, axis_env=[("pp", 4)])``. Returns a
    report with the ordered collective sequence and any deadlock-shaped
    issues.
    """
    jaxpr = jax.make_jaxpr(fn, axis_env=axis_env, **kwargs)(*args)
    report = CollectiveReport()
    _walk(jaxpr.jaxpr, report)
    return report


def assert_no_collective_deadlock(fn, *args, axis_env=None, **kwargs) -> CollectiveReport:
    report = lint_collectives(fn, *args, axis_env=axis_env, **kwargs)
    if not report.ok:
        raise RuntimeError(
            "collective deadlock lint failed:\n  " +
            "\n  ".join(str(i) for i in report.issues))
    return report
