"""Failure detection (ref: Fleet elastic / ``paddle.distributed.fleet``
fault-tolerance hooks; SURVEY.md §2.9/§5).

Two detectors:
  * NaN/inf sentinel — the Trainer skips poisoned updates in-graph (see
    trainer.py nan_guard) and raises WatchdogTrip after N bad steps.
  * Stall watchdog — a host thread that trips if the step callback hasn't
    been poked within `timeout_s` (hung collective / dead tunnel), running
    an emergency callback (e.g. checkpoint) before raising in the main
    thread via a flag the loop checks.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class WatchdogTrip(RuntimeError):
    pass


class StallWatchdog:
    def __init__(self, timeout_s: float = 600.0,
                 on_trip: Optional[Callable[[], None]] = None):
        self.timeout_s = timeout_s
        self.on_trip = on_trip
        self._last_poke = time.monotonic()
        self._tripped = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="pt-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def poke(self):
        self._last_poke = time.monotonic()
        if self._tripped.is_set():
            raise WatchdogTrip(
                f"no progress for > {self.timeout_s}s (stalled step detected)")

    def _run(self):
        while not self._stop.wait(min(self.timeout_s / 4, 30.0)):
            if time.monotonic() - self._last_poke > self.timeout_s:
                self._tripped.set()
                # the trip is detected on THIS thread — record + dump
                # here so a hung main thread (the very thing a watchdog
                # exists for) still leaves its flight file behind
                try:
                    from paddle_tpu.observability.flight import FLIGHT
                    FLIGHT.record("watchdog.trip", timeout_s=self.timeout_s)
                    FLIGHT.dump(reason="watchdog.trip")
                except Exception:
                    pass
                if self.on_trip:
                    try:
                        self.on_trip()
                    except Exception:
                        pass
                return

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def tripped(self) -> bool:
        return self._tripped.is_set()


def check_finite(tree) -> bool:
    """Host-side check that every float leaf is finite."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                return False
    return True
