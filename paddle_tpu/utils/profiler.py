"""Profiling & tracing (ref: ``python/paddle/profiler/`` — Profiler,
RecordEvent, chrome-trace export; SURVEY.md §2.9).

TPU-native: wraps ``jax.profiler`` (XLA's own tracer → TensorBoard/perfetto
trace with per-op HLO timings, HBM usage, ICI traffic) plus a host-side
step-timer with MFU accounting, and HLO/jaxpr dump helpers for graph debug.
"""
from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Optional

import jax


class Profiler:
    """Reference-shaped API: Profiler(targets=..., scheduler=...,
    on_trace_ready=...) ... start/stop. ``targets`` is accepted for parity
    (XLA traces always cover host + device); ``on_trace_ready`` runs
    BEFORE the trace starts so export_chrome_tracing can direct the
    output directory."""

    def __init__(self, log_dir: str = "profile_out", targets=None,
                 scheduler=None, on_trace_ready=None):
        self.log_dir = log_dir
        self.targets = targets
        self.scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._active = False

    def start(self):
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)  # may redirect self.log_dir
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def record_event(name: str):
    """Ref: paddle.profiler.RecordEvent — annotates the XLA trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> dict:
    """Per-device HBM usage (ref: paddle.device.cuda.memory_allocated)."""
    out = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
            out[str(d)] = {"bytes_in_use": s.get("bytes_in_use"),
                           "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                           "bytes_limit": s.get("bytes_limit")}
        except Exception:
            out[str(d)] = {}
    return out


@dataclass
class StepTimer:
    """Host-side step timing + MFU meter."""
    flops_per_token: float = 0.0
    peak_flops: float = 197e12
    _t0: float = field(default=0.0, repr=False)
    records: list = field(default_factory=list)

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, tokens: int = 0) -> dict:
        dt = time.perf_counter() - self._t0
        rec = {"step_s": dt}
        if tokens:
            rec["tokens_per_sec"] = tokens / dt
            if self.flops_per_token:
                rec["mfu"] = tokens / dt * self.flops_per_token / self.peak_flops
        self.records.append(rec)
        return rec


def dump_cost_analysis(fn, *args) -> dict:
    """XLA FLOPs/bytes estimate for `fn(*args)` (feeds MFU accounting)."""
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        return dict(compiled.cost_analysis())
    except Exception:
        return {}


def compiled_memory_analysis(fn, *args) -> dict:
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        m = compiled.memory_analysis()
        return {"temp_size": m.temp_size_in_bytes,
                "argument_size": m.argument_size_in_bytes,
                "output_size": m.output_size_in_bytes,
                "generated_code_size": m.generated_code_size_in_bytes}
    except Exception:
        return {}



class ProfilerTarget:
    """Ref profiler.ProfilerTarget — device classes to trace. On this
    stack traces always cover host + the XLA device."""
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "custom_device"
    TPU = "tpu"


class RecordEvent:
    """Ref profiler.RecordEvent: context manager/decorator annotating the
    trace (maps onto jax.profiler.TraceAnnotation)."""

    def __init__(self, name: str):
        self.name = name
        self._cm = None

    def begin(self):
        self._cm = jax.profiler.TraceAnnotation(self.name)
        self._cm.__enter__()

    def end(self):
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Ref profiler.make_scheduler — step-state schedule. Returns a
    callable step -> one of "closed"/"ready"/"record" mirroring the
    reference's ProfilerState for Profiler(scheduler=...)."""
    if record <= 0:
        raise ValueError("make_scheduler: record must be > 0")
    if closed < 0 or ready < 0:
        raise ValueError("make_scheduler: closed/ready must be >= 0")
    cycle = closed + ready + record

    def schedule(step: int) -> str:
        if step < skip_first:
            return "closed"
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return "closed"
        pos = s % cycle
        if pos < closed:
            return "closed"
        if pos < closed + ready:
            return "ready"
        return "record"

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """Ref profiler.export_chrome_tracing — the jax trace is already a
    TensorBoard/perfetto artifact; this callback (run by Profiler.start
    before tracing begins) directs it to ``dir_name``."""
    def on_export(prof):
        prof.log_dir = dir_name
        return dir_name
    return on_export
