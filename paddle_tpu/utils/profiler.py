"""Profiling & tracing (ref: ``python/paddle/profiler/`` — Profiler,
RecordEvent, chrome-trace export; SURVEY.md §2.9).

TPU-native: wraps ``jax.profiler`` (XLA's own tracer → TensorBoard/perfetto
trace with per-op HLO timings, HBM usage, ICI traffic) plus a host-side
step-timer with MFU accounting, and HLO/jaxpr dump helpers for graph debug.
"""
from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Optional

import jax


class Profiler:
    """Reference-shaped API: Profiler(targets=...) ... start/stop/export."""

    def __init__(self, log_dir: str = "profile_out"):
        self.log_dir = log_dir
        self._active = False

    def start(self):
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def record_event(name: str):
    """Ref: paddle.profiler.RecordEvent — annotates the XLA trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> dict:
    """Per-device HBM usage (ref: paddle.device.cuda.memory_allocated)."""
    out = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
            out[str(d)] = {"bytes_in_use": s.get("bytes_in_use"),
                           "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                           "bytes_limit": s.get("bytes_limit")}
        except Exception:
            out[str(d)] = {}
    return out


@dataclass
class StepTimer:
    """Host-side step timing + MFU meter."""
    flops_per_token: float = 0.0
    peak_flops: float = 197e12
    _t0: float = field(default=0.0, repr=False)
    records: list = field(default_factory=list)

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, tokens: int = 0) -> dict:
        dt = time.perf_counter() - self._t0
        rec = {"step_s": dt}
        if tokens:
            rec["tokens_per_sec"] = tokens / dt
            if self.flops_per_token:
                rec["mfu"] = tokens / dt * self.flops_per_token / self.peak_flops
        self.records.append(rec)
        return rec


def dump_cost_analysis(fn, *args) -> dict:
    """XLA FLOPs/bytes estimate for `fn(*args)` (feeds MFU accounting)."""
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        return dict(compiled.cost_analysis())
    except Exception:
        return {}


def compiled_memory_analysis(fn, *args) -> dict:
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        m = compiled.memory_analysis()
        return {"temp_size": m.temp_size_in_bytes,
                "argument_size": m.argument_size_in_bytes,
                "output_size": m.output_size_in_bytes,
                "generated_code_size": m.generated_code_size_in_bytes}
    except Exception:
        return {}
