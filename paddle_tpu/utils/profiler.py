"""Profiling & tracing (ref: ``python/paddle/profiler/`` — Profiler,
RecordEvent, chrome-trace export; SURVEY.md §2.9).

TPU-native: wraps ``jax.profiler`` (XLA's own tracer → TensorBoard/perfetto
trace with per-op HLO timings, HBM usage, ICI traffic) plus a host-side
step-timer with MFU accounting, and HLO/jaxpr dump helpers for graph debug.

Since the observability subsystem landed, the names here are THIN
DELEGATES: Profiler also drives the host-side span tracer (and writes
its Chrome trace next to the XLA artifact on stop), RecordEvent opens an
observability span alongside the XLA annotation, and StepTimer feeds the
shared ``train_tokens_per_sec``/``train_mfu`` gauges through the same
:func:`~paddle_tpu.observability.flops.record_throughput` choke point the
Trainer and bench.py use.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional

import jax

from paddle_tpu.observability import METRICS, TRACER, span as _span
from paddle_tpu.observability.flops import record_throughput

_STEPTIMER_S = METRICS.histogram(
    "steptimer_step_seconds", "wall time per StepTimer start/stop window")
_DEV_MEM = METRICS.gauge(
    "device_bytes_in_use", "per-device bytes in use (0 when the backend "
    "does not report memory stats)", labelnames=("device",))
_DEV_MEM_PEAK = METRICS.gauge(
    "device_bytes_peak", "per-device peak bytes in use (0 when the "
    "backend does not report memory stats)", labelnames=("device",))
_DEV_MEM_LIMIT = METRICS.gauge(
    "device_bytes_limit", "per-device memory capacity visible to the "
    "allocator (0 when the backend does not report it)",
    labelnames=("device",))


class Profiler:
    """Reference-shaped API: Profiler(targets=..., scheduler=...,
    on_trace_ready=...) ... start/stop. ``targets`` is accepted for parity
    (XLA traces always cover host + device); ``on_trace_ready`` runs
    BEFORE the trace starts so export_chrome_tracing can direct the
    output directory. Also drives the host span tracer: host spans are
    collected while active and written to ``<log_dir>/host_trace.json``
    (Chrome/Perfetto format) on stop."""

    def __init__(self, log_dir: str = "profile_out", targets=None,
                 scheduler=None, on_trace_ready=None):
        self.log_dir = log_dir
        self.targets = targets
        self.scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._active = False
        self._owns_tracer = False
        self.host_trace_path: Optional[str] = None

    def start(self):
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)  # may redirect self.log_dir
        jax.profiler.start_trace(self.log_dir)
        # only take over the host tracer if nobody else enabled it —
        # a surrounding `with TRACER:` keeps ownership of its buffer
        self._owns_tracer = not TRACER._enabled
        if self._owns_tracer:
            TRACER.enable()
        self._active = True
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            if self._owns_tracer:
                os.makedirs(self.log_dir, exist_ok=True)
                self.host_trace_path = os.path.join(
                    self.log_dir, "host_trace.json")
                TRACER.export_chrome_trace(self.host_trace_path)
                TRACER.disable()
                self._owns_tracer = False
            self._active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def record_event(name: str):
    """Ref: paddle.profiler.RecordEvent — annotates the XLA trace and the
    host span timeline."""
    with jax.profiler.TraceAnnotation(name), _span(name):
        yield


def device_memory_stats() -> dict:
    """Per-device HBM usage (ref: paddle.device.cuda.memory_allocated).
    Backends without memory stats (CPU) report explicit zeroed
    placeholders with the backend named, never an empty dict."""
    out = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats() or {}
        except Exception:
            s = {}
        if s:
            rec = {"backend": d.platform,
                   "bytes_in_use": s.get("bytes_in_use"),
                   "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                   "bytes_limit": s.get("bytes_limit")}
        else:
            rec = {"backend": d.platform, "bytes_in_use": 0,
                   "peak_bytes_in_use": 0, "bytes_limit": 0}
        out[str(d)] = rec
        _DEV_MEM.set(rec["bytes_in_use"] or 0, device=str(d))
        _DEV_MEM_PEAK.set(rec["peak_bytes_in_use"] or 0, device=str(d))
        _DEV_MEM_LIMIT.set(rec["bytes_limit"] or 0, device=str(d))
    return out


@dataclass
class StepTimer:
    """Host-side step timing + MFU meter. Each stop() also lands in the
    ``steptimer_step_seconds`` histogram and (when tokens are reported)
    the shared throughput/MFU gauges."""
    flops_per_token: float = 0.0
    peak_flops: float = 197e12
    _t0: float = field(default=0.0, repr=False)
    records: list = field(default_factory=list)

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, tokens: int = 0) -> dict:
        dt = time.perf_counter() - self._t0
        rec = {"step_s": dt}
        _STEPTIMER_S.observe(dt)
        if tokens and dt > 0:
            rec["tokens_per_sec"] = tokens / dt
            mfu = record_throughput(tokens / dt, self.flops_per_token,
                                    self.peak_flops)
            if self.flops_per_token:
                rec["mfu"] = mfu
        self.records.append(rec)
        return rec


def dump_cost_analysis(fn, *args) -> dict:
    """XLA FLOPs/bytes estimate for `fn(*args)` (feeds MFU accounting)."""
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        return dict(compiled.cost_analysis())
    except Exception:
        return {}


def compiled_memory_analysis(fn, *args) -> dict:
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        m = compiled.memory_analysis()
        return {"temp_size": m.temp_size_in_bytes,
                "argument_size": m.argument_size_in_bytes,
                "output_size": m.output_size_in_bytes,
                "generated_code_size": m.generated_code_size_in_bytes}
    except Exception:
        return {}



class ProfilerTarget:
    """Ref profiler.ProfilerTarget — device classes to trace. On this
    stack traces always cover host + the XLA device."""
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "custom_device"
    TPU = "tpu"


class RecordEvent:
    """Ref profiler.RecordEvent: context manager/decorator annotating the
    trace (maps onto jax.profiler.TraceAnnotation plus a host span)."""

    def __init__(self, name: str):
        self.name = name
        self._cm = None
        self._span = None

    def begin(self):
        self._cm = jax.profiler.TraceAnnotation(self.name)
        self._cm.__enter__()
        self._span = _span(self.name)
        self._span.__enter__()

    def end(self):
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Ref profiler.make_scheduler — step-state schedule. Returns a
    callable step -> one of "closed"/"ready"/"record" mirroring the
    reference's ProfilerState for Profiler(scheduler=...)."""
    if record <= 0:
        raise ValueError("make_scheduler: record must be > 0")
    if closed < 0 or ready < 0:
        raise ValueError("make_scheduler: closed/ready must be >= 0")
    cycle = closed + ready + record

    def schedule(step: int) -> str:
        if step < skip_first:
            return "closed"
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return "closed"
        pos = s % cycle
        if pos < closed:
            return "closed"
        if pos < closed + ready:
            return "ready"
        return "record"

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """Ref profiler.export_chrome_tracing — the jax trace is already a
    TensorBoard/perfetto artifact; this callback (run by Profiler.start
    before tracing begins) directs it to ``dir_name``."""
    def on_export(prof):
        prof.log_dir = dir_name
        return dir_name
    return on_export
