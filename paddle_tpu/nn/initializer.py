"""Parameter initializers (ref: ``python/paddle/nn/initializer/``)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.random import next_key


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]  # [in, out] reference linear layout
    rf = 1
    for s in shape[2:]:
        rf *= s
    return shape[1] * rf, shape[0] * rf  # conv OIHW


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        return jnp.full(shape, self.value, dtype=dtype or get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        key = key if key is not None else next_key()
        dtype = dtype or get_default_dtype()
        return self.mean + self.std * jax.random.normal(key, shape, jnp.float32).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        key = key if key is not None else next_key()
        dtype = dtype or get_default_dtype()
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None, key=None):
        key = key if key is not None else next_key()
        dtype = dtype or get_default_dtype()
        return jax.random.uniform(key, shape, jnp.float32, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None, key=None):
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return Normal(0.0, std)(shape, dtype, key)


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None, key=None):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return Uniform(-limit, limit)(shape, dtype, key)


class KaimingNormal(Initializer):
    def __init__(self, negative_slope=0.0, nonlinearity="relu"):
        self.a = negative_slope

    def __call__(self, shape, dtype=None, key=None):
        fan_in, _ = _fans(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        return Normal(0.0, gain / math.sqrt(fan_in))(shape, dtype, key)


class KaimingUniform(Initializer):
    def __init__(self, negative_slope=0.0, nonlinearity="relu"):
        self.a = negative_slope

    def __call__(self, shape, dtype=None, key=None):
        fan_in, _ = _fans(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        limit = gain * math.sqrt(3.0 / fan_in)
        return Uniform(-limit, limit)(shape, dtype, key)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None, key=None):
        key = key if key is not None else next_key()
        dtype = dtype or get_default_dtype()
        return self.gain * jax.nn.initializers.orthogonal()(key, shape, jnp.float32).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        arr = jnp.asarray(self.value, dtype=dtype or get_default_dtype())
        assert arr.shape == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return arr


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


class Dirac(Initializer):
    """Dirac delta for conv kernels (ref initializer/dirac.py): preserves
    channel identity through the conv — weight[i, i % in_c, center...] = 1,
    with ``groups`` replicating the identity per group."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None, key=None):
        dtype = dtype or get_default_dtype()
        assert len(shape) >= 3, "Dirac needs a conv kernel [out, in, *k]"
        out_c, in_c = shape[0], shape[1]
        w = jnp.zeros(shape, jnp.float32)
        centers = tuple(s // 2 for s in shape[2:])
        og = out_c // self.groups
        # per group, only the first min(og, in_c) out channels carry the
        # identity; surplus out channels stay ZERO (reference dirac_)
        per = min(og, in_c)
        idx_out = jnp.concatenate([
            jnp.arange(per) + g * og for g in range(self.groups)])
        idx_in = jnp.tile(jnp.arange(per), self.groups)
        w = w.at[(idx_out, idx_in) + tuple(
            jnp.full((per * self.groups,), c) for c in centers)].set(1.0)
        return w.astype(dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Ref initializer/set_global_initializer: default initializers used by
    layers when none is passed. Layers consult ``get_global_initializer``."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def get_global_initializer():
    return _global_weight_init, _global_bias_init


def default_weight_init(explicit, fallback):
    """Resolution order for a layer weight: explicit arg > global > layer
    default (the reference's create_parameter behavior). Layers whose
    reference counterpart passes an EXPLICIT initializer (BatchNorm/
    LayerNorm ones, PReLU 0.25, ...) keep it and are unaffected by the
    global default, matching the reference."""
    return explicit or _global_weight_init or fallback


def default_bias_init(fallback):
    return _global_bias_init or fallback
