"""Parameter utilities (ref: ``python/paddle/nn/utils/``): clip_grad_norm_,
clip_grad_value_, parameters_to_vector, vector_to_parameters, weight_norm,
spectral_norm.

Functional flavours: "in-place" reference APIs return NEW pytrees here
(params are immutable jax arrays)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
    "vector_to_parameters", "weight_norm", "remove_weight_norm",
    "spectral_norm",
]


def clip_grad_norm_(grads, max_norm, norm_type=2.0):
    """Global-norm clip over a grad pytree -> (clipped_grads, total_norm)."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if g is not None]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in leaves])) ** (1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(total, 1e-12))
    clipped = jax.tree_util.tree_map(
        lambda g: None if g is None else (g * scale).astype(g.dtype), grads,
        is_leaf=lambda x: x is None)
    return clipped, total


def clip_grad_value_(grads, clip_value):
    return jax.tree_util.tree_map(
        lambda g: None if g is None else jnp.clip(g, -clip_value, clip_value),
        grads, is_leaf=lambda x: x is None)


def parameters_to_vector(params):
    """Flatten a param pytree into one fp32 vector (ref torch/paddle util)."""
    leaves = jax.tree_util.tree_leaves(params)
    return jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])


def vector_to_parameters(vec, params_like):
    """Inverse of parameters_to_vector: reshape vec into the given pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(params_like)
    out, off = [], 0
    for l in leaves:
        n = l.size
        out.append(vec[off:off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def weight_norm(weight, dim=0, eps=1e-12):
    """Decompose weight into (g, v): weight = g * v / ||v|| along dim.
    Returns (g, v) — the trainable reparameterisation (ref:
    paddle.nn.utils.weight_norm). Use ``remove_weight_norm`` to re-fuse."""
    axes = tuple(i for i in range(weight.ndim) if i != dim % weight.ndim)
    g = jnp.sqrt(jnp.sum(jnp.square(weight.astype(jnp.float32)), axis=axes,
                         keepdims=True) + eps).astype(weight.dtype)
    return g, weight


def remove_weight_norm(g, v, dim=0, eps=1e-12):
    """Fuse (g, v) back into a plain weight."""
    axes = tuple(i for i in range(v.ndim) if i != dim % v.ndim)
    norm = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True) + eps).astype(v.dtype)
    return g * v / norm


def spectral_norm(weight, n_power_iterations=20, eps=1e-12, dim=0):
    """One-shot spectral normalisation of a weight (ref layer form lives at
    paddle_tpu.nn.SpectralNorm; this is the functional util)."""
    mat = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
    mat = mat.astype(jnp.float32)
    u = jnp.ones((mat.shape[0],), jnp.float32) / jnp.sqrt(mat.shape[0])
    for _ in range(n_power_iterations):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return (weight / sigma.astype(weight.dtype))
