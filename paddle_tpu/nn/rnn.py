"""Recurrent layers (ref: ``python/paddle/nn/layer/rnn.py``).

The reference runs cuDNN RNN kernels; on TPU the idiomatic lowering is a
``lax.scan`` over time with the gate matmuls batched so each step is one
MXU-friendly [B, 4H] GEMM. Layout: batch_first (B, T, C) like the reference
default ``time_major=False``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I


class _RNNCellBase(Module):
    def __init__(self, input_size, hidden_size, gates, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        k = 1.0 / jnp.sqrt(jnp.array(hidden_size, jnp.float32))
        init = I.Uniform(-float(k), float(k))
        self.weight_ih = init((input_size, gates * hidden_size), dtype)
        self.weight_hh = init((hidden_size, gates * hidden_size), dtype)
        self.bias_ih = init((gates * hidden_size,), dtype)
        self.bias_hh = init((gates * hidden_size,), dtype)
        self.input_size, self.hidden_size = input_size, hidden_size


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", dtype=None):
        super().__init__(input_size, hidden_size, 1, dtype)
        self.activation = activation

    def __call__(self, x, h):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        return act(x @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh)


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, dtype=None):
        super().__init__(input_size, hidden_size, 4, dtype)

    def __call__(self, x, state):
        h, c = state
        gates = x @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, dtype=None):
        super().__init__(input_size, hidden_size, 3, dtype)

    def __call__(self, x, h):
        gi = x @ self.weight_ih + self.bias_ih
        gh = h @ self.weight_hh + self.bias_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        return (1 - z) * n + z * h


class _RNNBase(Module):
    cell_cls = None
    is_lstm = False

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 dtype=None, **cell_kw):
        super().__init__()
        self.bidirectional = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirectional else 1
        cells = []
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * ndir
            cells.append(self.cell_cls(in_size, hidden_size, dtype=dtype, **cell_kw))
            if self.bidirectional:
                cells.append(self.cell_cls(in_size, hidden_size, dtype=dtype, **cell_kw))
        self.cells = cells
        self.num_layers, self.hidden_size = num_layers, hidden_size

    def _zero_state(self, cell, batch, dtype):
        h = jnp.zeros((batch, cell.hidden_size), dtype)
        return (h, jnp.zeros_like(h)) if self.is_lstm else h

    def _run_cell(self, cell, x_tbc, init_state, reverse=False):
        if reverse:
            x_tbc = jnp.flip(x_tbc, axis=0)

        def step(state, xt):
            if self.is_lstm:
                h, state = cell(xt, state)
            else:
                state = cell(xt, state)
                h = state
            return state, h

        final, ys = lax.scan(step, init_state, x_tbc)
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return final, ys

    def __call__(self, x, initial_states=None):
        # x: [B, T, C] -> scan over T
        x_tbc = jnp.swapaxes(x, 0, 1)
        ndir = 2 if self.bidirectional else 1
        finals = []
        for layer in range(self.num_layers):
            cell_f = self.cells[layer * ndir]
            st = (initial_states[layer * ndir] if initial_states is not None
                  else self._zero_state(cell_f, x.shape[0], x.dtype))
            final_f, ys_f = self._run_cell(cell_f, x_tbc, st)
            if self.bidirectional:
                cell_b = self.cells[layer * ndir + 1]
                st_b = (initial_states[layer * ndir + 1] if initial_states is not None
                        else self._zero_state(cell_b, x.shape[0], x.dtype))
                final_b, ys_b = self._run_cell(cell_b, x_tbc, st_b, reverse=True)
                x_tbc = jnp.concatenate([ys_f, ys_b], axis=-1)
                finals += [final_f, final_b]
            else:
                x_tbc = ys_f
                finals.append(final_f)
        return jnp.swapaxes(x_tbc, 0, 1), finals


def _cell_step(cell, xt, state):
    """Uniform (h, new_state) protocol: a cell may return either the new
    state alone (SimpleRNN/GRU convention) or an (outputs, new_states)
    pair (LSTMCell and the reference's RNNCellBase contract)."""
    out = cell(xt, state)
    if isinstance(out, tuple) and len(out) == 2:
        return out
    return out, out


def _cell_zero_state(cell, batch, dtype):
    if hasattr(cell, "get_initial_states"):
        return cell.get_initial_states(batch, dtype)
    h = jnp.zeros((batch, cell.hidden_size), dtype)
    return (h, jnp.zeros_like(h)) if isinstance(cell, LSTMCell) else h


class RNN(Module):
    """Generic cell driver (ref ``python/paddle/nn/layer/rnn.py`` class RNN).

    Wraps any single-step cell and scans it over time with ``lax.scan``.
    ``forward(inputs, initial_states)`` -> ``(outputs, final_states)``.
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def __call__(self, inputs, initial_states=None):
        x_tbc = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        batch = x_tbc.shape[1]
        state = (initial_states if initial_states is not None
                 else _cell_zero_state(self.cell, batch, x_tbc.dtype))
        if self.is_reverse:
            x_tbc = jnp.flip(x_tbc, axis=0)

        def step(st, xt):
            h, st = _cell_step(self.cell, xt, st)
            return st, h

        final, ys = lax.scan(step, state, x_tbc)
        if self.is_reverse:
            ys = jnp.flip(ys, axis=0)
        outputs = ys if self.time_major else jnp.swapaxes(ys, 0, 1)
        return outputs, final


class BiRNN(Module):
    """Bidirectional cell driver (ref rnn.py class BiRNN): runs ``cell_fw``
    forward and ``cell_bw`` reversed, concatenating outputs on the feature
    axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def __call__(self, inputs, initial_states=None):
        st_fw, st_bw = (None, None) if initial_states is None else initial_states
        out_fw, fin_fw = self.fw(inputs, st_fw)
        out_bw, fin_bw = self.bw(inputs, st_bw)
        return jnp.concatenate([out_fw, out_bw], axis=-1), (fin_fw, fin_bw)


class SimpleRNN(_RNNBase):
    cell_cls = SimpleRNNCell


class LSTM(_RNNBase):
    cell_cls = LSTMCell
    is_lstm = True


class GRU(_RNNBase):
    cell_cls = GRUCell
