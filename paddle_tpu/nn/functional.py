"""Functional NN ops (ref: ``python/paddle/nn/functional/``).

All pure functions; layers in paddle_tpu.nn wrap these. Convs/pools use
``lax.conv_general_dilated`` / ``lax.reduce_window`` which XLA maps onto the
MXU / vector unit directly. Data format default NCHW for reference parity
(XLA transposes to its preferred layout internally on TPU).
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

# -- activations (ref functional/activation.py) -----------------------------

relu = jax.nn.relu
relu6 = jax.nn.relu6
sigmoid = jax.nn.sigmoid
log_sigmoid = jax.nn.log_sigmoid
softplus = jax.nn.softplus
silu = jax.nn.silu
swish = jax.nn.silu
mish = lambda x: x * jnp.tanh(jax.nn.softplus(x))
tanh = jnp.tanh
hardswish = jax.nn.hard_swish
hardsigmoid = jax.nn.hard_sigmoid
hardtanh = lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max)
elu = jax.nn.elu
celu = jax.nn.celu
selu = jax.nn.selu
leaky_relu = lambda x, negative_slope=0.01: jax.nn.leaky_relu(x, negative_slope)
prelu = lambda x, weight: jnp.where(x >= 0, x, weight * x)
rrelu = lambda x, lower=1/8., upper=1/3., training=False: leaky_relu(x, (lower+upper)/2)
softshrink = lambda x, threshold=0.5: jnp.sign(x) * jnp.maximum(jnp.abs(x) - threshold, 0)
hardshrink = lambda x, threshold=0.5: jnp.where(jnp.abs(x) > threshold, x, 0)
tanhshrink = lambda x: x - jnp.tanh(x)
softsign = jax.nn.soft_sign
thresholded_relu = lambda x, threshold=1.0: jnp.where(x > threshold, x, 0)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, *, rng):
    g = jax.random.gumbel(rng, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:  # straight-through: hard one-hot forward, soft gradient
        idx = jnp.argmax(y, axis=axis)
        one = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
        y = one + y - lax.stop_gradient(y)
    return y


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def swiglu(x, y=None):
    """Ref: paddle.incubate.nn.functional.swiglu (LLaMA MLP gate)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(jnp.reshape(x, new), axis=axis + 1)


# -- linear / embedding -----------------------------------------------------

def linear(x, weight, bias=None):
    """weight layout [in, out] — reference convention (paddle stores [in,out],
    unlike torch's [out,in]); maps directly to x @ w on the MXU."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def embedding(x, weight, padding_idx=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2]
    y = jnp.einsum("...i,oij,...j->...o", x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


# -- dropout ----------------------------------------------------------------

def dropout(x, p=0.5, training=True, *, rng=None, axis=None):
    if not training or p == 0.0:
        return x
    if rng is None:
        from paddle_tpu.core.random import next_key
        rng = next_key()
    keep = 1.0 - p
    shape = list(x.shape)
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    mask = jax.random.bernoulli(rng, keep, tuple(shape))
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, *, rng=None):
    return dropout(x, p, training, rng=rng, axis=(0, 1))  # drop whole channels NCHW


def dropout3d(x, p=0.5, training=True, *, rng=None):
    return dropout(x, p, training, rng=rng, axis=(0, 1))  # NCDHW channel drop


def alpha_dropout(x, p=0.5, training=True, *, rng=None):
    if not training or p == 0.0:
        return x
    if rng is None:
        from paddle_tpu.core.random import next_key
        rng = next_key()
    alpha = -1.7580993408473766
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    a = (keep + alpha ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha * (1 - keep)
    return (a * jnp.where(mask, x, alpha) + b).astype(x.dtype)


# -- normalization (ref functional/norm.py) ---------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(x.ndim - len(tuple(normalized_shape) if not isinstance(normalized_shape, int) else (normalized_shape,)), x.ndim))
    # statistics in fp32: bf16 mean/var loses ~3 decimal digits, which is
    # visible in deep pre-LN stacks
    x32 = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.var(x32, axis=axes, keepdims=True)
    out = (x32 - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(out.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6):
    """Ref: paddle.incubate.nn.functional.fused_rms_norm — compute in fp32,
    cast back (bf16-safe)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    out = x32 * lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    """Returns (out, new_mean, new_var). Reference semantics: momentum is the
    decay on the RUNNING stat (new = m*old + (1-m)*batch)."""
    caxis = 1 if data_format == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    if training:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        n = x.size / x.shape[caxis]
        unbiased = var * n / jnp.maximum(n - 1, 1)
        new_mean = momentum * running_mean + (1 - momentum) * mean
        new_var = momentum * running_var + (1 - momentum) * unbiased
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    shape = [1] * x.ndim
    shape[caxis] = x.shape[caxis]
    out = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype), new_mean, new_var


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    g = num_groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + epsilon)
    out = xg.reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out.astype(x.dtype)


def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
        if bias is not None:
            out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def normalize(x, p=2, axis=1, epsilon=1e-12):
    from paddle_tpu.tensor import norm as t_norm
    n = t_norm(x, p=p, axis=axis, keepdim=True)
    return x / jnp.maximum(n, epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[1] = (half, size - half - 1)
    padded = jnp.pad(sq, pad_cfg)
    win = sum(lax.slice_in_dim(padded, i, i + x.shape[1], axis=1) for i in range(size))
    return x / jnp.power(k + alpha * win / size, beta)


# -- conv (ref functional/conv.py) ------------------------------------------

def _norm_tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """weight: [out_c, in_c/groups, kh, kw] (reference layout)."""
    nd = 2
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm_tuple(padding, nd)
        pad = [(pi, pi) for pi in p]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)
    out = out.astype(x.dtype)
    if bias is not None:
        shape = [1] * out.ndim
        shape[1 if data_format == "NCHW" else -1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    x4 = x[..., None]
    w4 = weight[..., None]
    out = conv2d(x4, w4, bias,
                 stride=(_norm_tuple(stride, 1)[0], 1),
                 padding=((_norm_tuple(padding, 1)[0],) * 2, (0, 0)) if not isinstance(padding, str) else padding,
                 dilation=(_norm_tuple(dilation, 1)[0], 1), groups=groups)
    return out[..., 0]


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    nd = 3
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm_tuple(padding, nd)
        pad = [(pi, pi) for pi in p]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(x, weight, stride, pad, rhs_dilation=dilation,
                                   dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape((1, -1, 1, 1, 1))
    return out


def _conv_transpose(x, weight, nd, bias=None, stride=1, padding=0,
                    output_padding=0, dilation=1, groups=1):
    """Generic N-D transpose conv: lhs-dilated conv with the flipped kernel.
    weight: [in_c, out_c/groups, *k] (reference transpose-conv layout)."""
    stride = _norm_tuple(stride, nd)
    p = _norm_tuple(padding, nd)
    op = _norm_tuple(output_padding, nd)
    dilation = _norm_tuple(dilation, nd)
    kdims = weight.shape[2:]
    if groups > 1:
        ic = x.shape[1]
        oc_g = weight.shape[1]
        w = weight.reshape((groups, ic // groups, oc_g) + kdims)
        w = jnp.flip(w, axis=tuple(range(3, 3 + nd)))
        w = jnp.swapaxes(w, 1, 2).reshape((groups * oc_g, ic // groups) + kdims)
    else:
        w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
        w = jnp.swapaxes(w, 0, 1)  # -> [out_c, in_c, *k]
    pad = [(dilation[i] * (k - 1) - p[i], dilation[i] * (k - 1) - p[i] + op[i])
           for i, k in enumerate(kdims)]
    sp = "HWD"[:nd] if nd < 3 else "DHW"
    fmt = ("NC" + sp, "OI" + sp, "NC" + sp)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, fmt)
    out = lax.conv_general_dilated(x, w, window_strides=(1,) * nd, padding=pad,
                                   lhs_dilation=stride, rhs_dilation=dilation,
                                   dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1):
    """weight: [in_c, out_c/groups, kh, kw] (reference transpose-conv layout)."""
    return _conv_transpose(x, weight, 2, bias, stride, padding, output_padding,
                           dilation, groups)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1):
    return _conv_transpose(x, weight, 1, bias, stride, padding, output_padding,
                           dilation, groups)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1):
    return _conv_transpose(x, weight, 3, bias, stride, padding, output_padding,
                           dilation, groups)


def unfold(x, kernel_size, stride=1, padding=0, dilation=1):
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride, 2)
    p = _norm_tuple(padding, 2)
    d = _norm_tuple(dilation, 2)
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * k[0] * k[1], -1)


# -- pooling (ref functional/pooling.py) ------------------------------------

def _pool(x, init, op, kernel, stride, padding, data_format="NCHW"):
    nd = x.ndim - 2
    kernel = _norm_tuple(kernel, nd)
    stride = _norm_tuple(stride or kernel, nd)
    p = _norm_tuple(padding, nd)
    if data_format == "NCHW":
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    else:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + tuple((pi, pi) for pi in p) + ((0, 0),)
    return lax.reduce_window(x, init, op, dims, strides, pads)


def _max_pool_with_mask(x, kernel, stride, padding):
    """Max pool returning (out, flat-argmax-indices) — ref pooling.py
    ``return_mask=True``. NC{spatial} layout; indices are flat over the
    *unpadded* spatial dims, matching the reference. Built on patch
    extraction so it stays one fused XLA op chain (no host loops)."""
    nd = x.ndim - 2
    k = _norm_tuple(kernel, nd)
    s = _norm_tuple(stride or kernel, nd)
    p = _norm_tuple(padding, nd)
    # finite dtype-min, not -inf: patch extraction is a conv with a 0/1
    # identity kernel and 0 * -inf would poison borders with NaN
    neg = jnp.asarray(jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.iinfo(x.dtype).min, x.dtype)
    xp = jnp.pad(x, ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p),
                 constant_values=neg)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=k, window_strides=s,
        padding=[(0, 0)] * nd,
        dimension_numbers=("NC" + "HWD"[:nd], "OI" + "HWD"[:nd],
                           "NC" + "HWD"[:nd]))
    out_sp = patches.shape[2:]
    ksize = 1
    for ki in k:
        ksize *= ki
    pr = patches.reshape((n, c, ksize) + out_sp)
    out = pr.max(axis=2)
    arg = pr.argmax(axis=2)  # window-local flat index, (k0, k1, ...) order
    # decompose local index into per-dim offsets, add window origin, un-pad
    flat = jnp.zeros_like(arg)
    rem = arg
    for d in range(nd):
        tail = 1
        for ki in k[d + 1:]:
            tail *= ki
        loc = rem // tail
        rem = rem % tail
        origin = jnp.arange(out_sp[d]) * s[d] - p[d]
        origin = origin.reshape((1, 1) + tuple(
            out_sp[d] if i == d else 1 for i in range(nd)))
        gidx = loc + origin
        tail_sp = 1
        for si in spatial[d + 1:]:
            tail_sp *= si
        flat = flat + gidx * tail_sp
    return out, flat


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW",
               return_mask=False):
    if return_mask:
        assert data_format == "NCHW"
        return _max_pool_with_mask(x, kernel_size, stride, padding)
    return _pool(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                 lax.max, kernel_size, stride, padding, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NCDHW",
               return_mask=False):
    if return_mask:
        assert data_format == "NCDHW"
        return _max_pool_with_mask(x, kernel_size, stride, padding)
    # _pool only distinguishes channel-first vs channel-last
    fmt = "NCHW" if data_format == "NCDHW" else "NHWC"
    return _pool(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                 lax.max, kernel_size, stride, padding, fmt)


def avg_pool3d(x, kernel_size, stride=None, padding=0, data_format="NCDHW",
               exclusive=True):
    fmt = "NCHW" if data_format == "NCDHW" else "NHWC"
    return avg_pool2d(x, kernel_size, stride, padding, fmt, exclusive)


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, nd):
    k = _norm_tuple(kernel_size, nd)
    s = _norm_tuple(stride or kernel_size, nd)
    p = _norm_tuple(padding, nd)
    n, c = x.shape[:2]
    in_sp = x.shape[2:]
    if output_size is None:
        out_sp = tuple((in_sp[d] - 1) * s[d] - 2 * p[d] + k[d]
                       for d in range(nd))
    else:
        out_sp = tuple(output_size[-nd:])
    total = 1
    for si in out_sp:
        total *= si
    vals = x.reshape(n, c, -1)
    idx = indices.reshape(n, c, -1)
    flat = jnp.zeros((n, c, total), x.dtype)
    out = flat.at[jnp.arange(n)[:, None, None],
                  jnp.arange(c)[None, :, None], idx].set(vals)
    return out.reshape((n, c) + out_sp)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    """Inverse of max_pool1d with return_mask (ref pooling.py:max_unpool1d)."""
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 3)


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW",
               exclusive=True):
    nd = x.ndim - 2
    summed = _pool(x, 0.0, lax.add, kernel_size, stride, padding, data_format)
    if exclusive and padding != 0:
        ones = jnp.ones_like(x)
        counts = _pool(ones, 0.0, lax.add, kernel_size, stride, padding, data_format)
        return summed / counts
    k = _norm_tuple(kernel_size, nd)
    denom = 1
    for ki in k:
        denom *= ki
    return summed / denom


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding)
    return max_pool2d(x[..., None], (_norm_tuple(kernel_size, 1)[0], 1),
                      (_norm_tuple(stride or kernel_size, 1)[0], 1),
                      (_norm_tuple(padding, 1)[0], 0))[..., 0]


def avg_pool1d(x, kernel_size, stride=None, padding=0):
    return avg_pool2d(x[..., None], (_norm_tuple(kernel_size, 1)[0], 1),
                      (_norm_tuple(stride or kernel_size, 1)[0], 1),
                      (_norm_tuple(padding, 1)[0], 0))[..., 0]


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    out = _norm_tuple(output_size, 2)
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    if h % out[0] == 0 and w % out[1] == 0:
        xr = x.reshape(n, c, out[0], h // out[0], out[1], w // out[1])
        y = xr.mean(axis=(3, 5))
    else:
        y = _adaptive_avg_along(_adaptive_avg_along(x, 2, out[0]), 3, out[1])
    if data_format != "NCHW":
        y = jnp.moveaxis(y, 1, -1)
    return y


def adaptive_max_pool2d(x, output_size):
    out = _norm_tuple(output_size, 2)
    n, c, h, w = x.shape
    assert h % out[0] == 0 and w % out[1] == 0, "adaptive_max needs divisible sizes"
    xr = x.reshape(n, c, out[0], h // out[0], out[1], w // out[1])
    return xr.max(axis=(3, 5))


# -- interpolate ------------------------------------------------------------

def _resize_axis(x, axis, out_size, mode, align_corners):
    """Separable 1-axis resize matching reference (torch/paddle) coordinate
    conventions: nearest = floor(out*in/out) asymmetric; linear = half-pixel
    centers unless align_corners."""
    in_size = x.shape[axis]
    if in_size == out_size:
        return x
    if mode == "nearest":
        idx = jnp.floor(jnp.arange(out_size) * (in_size / out_size)).astype(jnp.int32)
        return jnp.take(x, jnp.clip(idx, 0, in_size - 1), axis=axis)
    if align_corners and out_size > 1:
        coords = jnp.arange(out_size) * ((in_size - 1) / (out_size - 1))
    else:
        coords = (jnp.arange(out_size) + 0.5) * (in_size / out_size) - 0.5
    shape = [1] * x.ndim
    shape[axis] = out_size
    if mode == "cubic":
        # Keys cubic kernel, a=-0.75 (reference/torch bicubic), border-clamped
        a = -0.75
        base = jnp.floor(coords).astype(jnp.int32)
        t = (coords - base).astype(jnp.float32)

        def k1(u):  # |u| <= 1
            return (a + 2) * u ** 3 - (a + 3) * u ** 2 + 1

        def k2(u):  # 1 < |u| < 2
            return a * u ** 3 - 5 * a * u ** 2 + 8 * a * u - 4 * a

        ws = [k2(t + 1), k1(t), k1(1 - t), k2(2 - t)]
        y = 0.0
        for off, w in zip((-1, 0, 1, 2), ws):
            idx = jnp.clip(base + off, 0, in_size - 1)
            y = y + jnp.take(x, idx, axis=axis).astype(jnp.float32) * w.reshape(shape)
        return y.astype(x.dtype)
    coords = jnp.clip(coords, 0.0, in_size - 1)
    lo = jnp.clip(jnp.floor(coords).astype(jnp.int32), 0, in_size - 1)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    w = (coords - lo).astype(jnp.float32).reshape(shape)
    xlo = jnp.take(x, lo, axis=axis).astype(jnp.float32)
    xhi = jnp.take(x, hi, axis=axis).astype(jnp.float32)
    return (xlo * (1 - w) + xhi * w).astype(x.dtype)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format=None):
    """N-D resize: 3D (linear), 4D (nearest/bilinear/bicubic/area), 5D
    (nearest/trilinear). Ref: paddle.nn.functional.interpolate."""
    nd = x.ndim - 2
    channels_last = data_format in ("NHWC", "NLC", "NDHWC")
    if channels_last:
        x = jnp.moveaxis(x, -1, 1)
    spatial = x.shape[2:]
    if size is None:
        sf = ((scale_factor,) * nd if isinstance(scale_factor, (int, float))
              else tuple(scale_factor))
        size = tuple(int(s * f) for s, f in zip(spatial, sf))
    size = _norm_tuple(size, nd)
    if mode == "area":
        for axis, o in zip(range(2, 2 + nd), size):
            x = _adaptive_avg_along(x, axis, o)
        y = x
    else:
        m = {"nearest": "nearest", "bicubic": "cubic", "linear": "linear",
             "bilinear": "linear", "trilinear": "linear"}[mode]
        y = x
        for axis, o in zip(range(2, 2 + nd), size):
            y = _resize_axis(y, axis, o, m, align_corners)
    if channels_last:
        y = jnp.moveaxis(y, 1, -1)
    return y


upsample = interpolate


def pixel_shuffle(x, upscale_factor):
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


# -- losses (ref functional/loss.py) ----------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, label_smoothing=0.0):
    """Reference: paddle.nn.functional.cross_entropy — input is logits."""
    logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis)
    num_classes = input.shape[axis]
    if soft_label:
        target = label.astype(jnp.float32)
    else:
        target = jax.nn.one_hot(label, num_classes, axis=axis, dtype=jnp.float32)
    if label_smoothing > 0.0:
        target = target * (1 - label_smoothing) + label_smoothing / num_classes
    loss = -jnp.sum(target * logp, axis=axis)
    if weight is not None and not soft_label:
        w = jnp.take(weight, jnp.clip(label, 0, num_classes - 1))
        loss = loss * w
    if not soft_label and ignore_index is not None:
        mask = (label != ignore_index).astype(loss.dtype)
        loss = loss * mask
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    ll = -jnp.take_along_axis(input, label[..., None], axis=-1)[..., 0]
    if weight is not None:
        ll = ll * jnp.take(weight, label)
    mask = (label != ignore_index).astype(ll.dtype)
    ll = ll * mask
    if reduction == "mean":
        return jnp.sum(ll) / jnp.maximum(jnp.sum(mask), 1.0)
    return _reduce(ll, reduction)


def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, None)) +
             (1 - label) * jnp.log(jnp.clip(1 - input, eps, None)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None):
    neg_abs = -jnp.abs(logit)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(neg_abs)) +
                                              jnp.maximum(-logit, 0))
    else:
        loss = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1) + 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0.0))
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return _reduce(jnp.maximum(-label * (input - other) + margin, 0.0), reduction)


def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0, reduction="mean"):
    dp = jnp.linalg.norm(anchor - positive, ord=p, axis=-1)
    dn = jnp.linalg.norm(anchor - negative, ord=p, axis=-1)
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


def label_smooth(label, epsilon=0.1):
    k = label.shape[-1]
    return label * (1 - epsilon) + epsilon / k


def sigmoid_focal_loss(logit, label, alpha=0.25, gamma=2.0, reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    return _reduce(a_t * ((1 - p_t) ** gamma) * ce, reduction)


def square_error_cost(input, label):
    return jnp.square(input - label)


# -- attention (ref functional/flash_attention.py & fused kernels) ----------

def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 *, rng=None, scale=None, window=None,
                                 kv_lens=None):
    """[B, S, H, D] layout (reference flash_attention convention).

    Dispatches to the Pallas TPU flash kernel when available, else a fused
    XLA path (softmax in fp32, MXU matmuls in input dtype). ``window`` is a
    Mistral-style causal sliding window. ``kv_lens`` ([B] ints) is the
    padded-varlen path — key padding expressed as lengths keeps the fused
    kernel (a dense attn_mask always falls back to XLA).
    """
    from paddle_tpu.ops import attention as _attn
    return _attn.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training, rng=rng, scale=scale,
        window=window, kv_lens=kv_lens)


def softmax_mask_fuse_upper_triangle(x):
    s = x.shape[-1]
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    x = jnp.where(mask, x, -1e9)
    return jax.nn.softmax(x, axis=-1)


# -- one-hot / sequence ------------------------------------------------------

def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


# -- distance / similarity (ref functional/distance.py) ----------------------

def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


# -- extra losses (ref functional/loss.py) -----------------------------------

def soft_margin_loss(input, label, reduction="mean"):
    return _reduce(jax.nn.softplus(-label * input), reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input) +
             (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    return _reduce(jnp.mean(loss, axis=-1), reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean"):
    num_classes = input.shape[-1]
    x_y = jnp.take_along_axis(input, label[..., None], axis=-1)
    m = jnp.maximum(margin - x_y + input, 0.0) ** p
    if weight is not None:
        m = m * jnp.take(weight, label)[..., None]
    # the j == y term is excluded from the sum
    m = m * (1 - jax.nn.one_hot(label, num_classes, dtype=m.dtype))
    return _reduce(jnp.sum(m, axis=-1) / num_classes, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(jnp.maximum(label, 1.0)) - label + \
            0.5 * jnp.log(2 * math.pi * jnp.maximum(label, 1.0))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean"):
    """CTC alpha-recursion in log space via ``lax.scan`` over time.

    Ref: paddle.nn.functional.ctc_loss (warpctc kernel,
    ``paddle/phi/kernels/impl/warpctc_kernel_impl.h``). TPU-native: the
    whole forward DP is one scan, batch-vectorised, no host sync.

    ``log_probs``: [T, B, C] log-softmax-normalised; ``labels``: [B, L] padded.
    """
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    neg_inf = jnp.float32(-1e30)
    log_probs = log_probs.astype(jnp.float32)

    s = jnp.arange(S)
    lab_idx = jnp.clip((s - 1) // 2, 0, L - 1)
    ext = jnp.where(s[None, :] % 2 == 0, blank, labels[:, lab_idx])  # [B, S]
    # skip transition s-2 -> s allowed when ext[s] != blank and != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, ext.dtype), ext[:, :-2]], axis=1)
    allow_skip = (ext != blank) & (ext != ext_m2) & (s[None, :] >= 2)

    def emit(lp_t):  # [B, C] -> [B, S]
        return jnp.take_along_axis(lp_t, ext, axis=1)

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, :, blank])
    if L > 0:
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(log_probs[0], ext[:, 1:2], axis=1)[:, 0])

    def step(alpha, lp_t):
        a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(allow_skip, a2, neg_inf)
        stacked = jnp.stack([alpha, a1, a2], axis=0)
        new = jax.scipy.special.logsumexp(stacked, axis=0) + emit(lp_t)
        return new, new

    _, alphas = lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]

    # per-sample final alpha at t = input_length - 1
    tb = alphas.transpose(1, 0, 2)  # [B, T, S]
    a_final = jnp.take_along_axis(
        tb, (input_lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # [B, S]
    end = (2 * label_lengths).astype(jnp.int32)  # index of last blank
    a_last = jnp.take_along_axis(a_final, end[:, None], axis=1)[:, 0]
    a_prev = jnp.where(
        end - 1 >= 0,
        jnp.take_along_axis(a_final, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0],
        neg_inf)
    loss = -jnp.logaddexp(a_last, a_prev)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths.astype(loss.dtype), 1.0))
    return _reduce(loss, reduction)


# -- fold / shuffle (ref functional/common.py) --------------------------------

def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """Inverse of :func:`unfold` — scatter-add col patches back to an image."""
    H, W = _norm_tuple(output_sizes, 2)
    kh, kw = _norm_tuple(kernel_sizes, 2)
    sh, sw = _norm_tuple(strides, 2)
    ph, pw = _norm_tuple(paddings, 2)
    dh, dw = _norm_tuple(dilations, 2)
    N = x.shape[0]
    C = x.shape[1] // (kh * kw)
    nh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    x6 = x.reshape(N, C, kh, kw, nh, nw)
    rows = (jnp.arange(kh) * dh)[:, None] + (jnp.arange(nh) * sh)[None, :]  # [kh, nh]
    cols = (jnp.arange(kw) * dw)[:, None] + (jnp.arange(nw) * sw)[None, :]  # [kw, nw]
    out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), x.dtype)
    out = out.at[:, :, rows[:, None, :, None], cols[None, :, None, :]].add(x6)
    return out[:, :, ph:ph + H, pw:pw + W]


def pixel_unshuffle(x, downscale_factor):
    n, c, h, w = x.shape
    r = downscale_factor
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, c * r * r, h // r, w // r)


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    return jnp.swapaxes(x, 1, 2).reshape(n, c, h, w)


# -- adaptive pooling with exact window semantics ----------------------------

def _adaptive_avg_matrix(in_size, out_size, dtype):
    """[out, in] averaging matrix: row i averages window
    [floor(i*in/out), ceil((i+1)*in/out))."""
    import numpy as _np
    m = _np.zeros((out_size, in_size), _np.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)  # ceil
        m[i, lo:hi] = 1.0 / (hi - lo)
    return jnp.asarray(m, dtype)


def _adaptive_avg_along(x, axis, out_size):
    if x.shape[axis] == out_size:
        return x
    m = _adaptive_avg_matrix(x.shape[axis], out_size, jnp.float32)
    # HIGHEST: keep fp32 MXU accumulation — window means must be exact, and
    # this runs on tiny [out, in] matrices so the cost is nil
    y = jnp.matmul(jnp.moveaxis(x, axis, -1).astype(jnp.float32), m.T,
                   precision=lax.Precision.HIGHEST)
    return jnp.moveaxis(y, -1, axis).astype(x.dtype)


def adaptive_avg_pool1d(x, output_size):
    return _adaptive_avg_along(x, -1, output_size if isinstance(output_size, int)
                               else output_size[0])


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    out = _norm_tuple(output_size, 3)
    if data_format != "NCDHW":
        x = jnp.moveaxis(x, -1, 1)
    for axis, o in zip((-3, -2, -1), out):
        x = _adaptive_avg_along(x, axis, o)
    if data_format != "NCDHW":
        x = jnp.moveaxis(x, 1, -1)
    return x


def adaptive_max_pool1d(x, output_size):
    out = output_size if isinstance(output_size, int) else output_size[0]
    n, c, l = x.shape
    if l % out == 0:
        return x.reshape(n, c, out, l // out).max(axis=-1)
    # non-divisible: windowed max via masked segments
    m = _adaptive_avg_matrix(l, out, jnp.float32) > 0  # [out, in] membership
    big = jnp.where(m[None, None], x[:, :, None, :], -jnp.inf)
    return big.max(axis=-1).astype(x.dtype)


def sequence_mask(lengths, maxlen=None, dtype="bool"):
    maxlen = maxlen or int(jnp.max(lengths))
    row = jnp.arange(maxlen)
    return (row[None, :] < lengths[:, None]).astype(dtype)


# -- spatial samplers (ref functional/vision.py: grid_sample / affine_grid) ---

def affine_grid(theta, out_shape, align_corners=True):
    """Sampling grid from a batch of affine matrices.

    ``theta`` is [N, 2, 3] with ``out_shape`` (N, C, H, W) → grid [N, H, W, 2],
    or [N, 3, 4] with (N, C, D, H, W) → [N, D, H, W, 3]. Grid coords are in
    [-1, 1], last axis ordered (x, y[, z]) fastest-varying-first as in the
    reference (``python/paddle/nn/functional/vision.py``).
    """
    theta = jnp.asarray(theta)
    spatial = out_shape[2:]
    nd = len(spatial)

    def base(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size, dtype=theta.dtype)
        step = 2.0 / size
        return jnp.arange(size, dtype=theta.dtype) * step + (step / 2 - 1.0)

    # axes in (x, y, z) order = reversed spatial order
    axes = [base(s) for s in reversed(spatial)]
    mesh = jnp.meshgrid(*axes, indexing="ij")  # each [W,H(,D)] ordered x-major
    # want output laid out [D,]H,W with last dim (x,y,z): stack then transpose
    coords = jnp.stack([m for m in mesh], axis=-1)  # [W, H(, D), nd]
    coords = jnp.transpose(coords, tuple(range(nd - 1, -1, -1)) + (nd,))  # [(D,)H,W,nd]
    ones = jnp.ones(coords.shape[:-1] + (1,), theta.dtype)
    hom = jnp.concatenate([coords, ones], axis=-1)          # [(D,)H,W,nd+1]
    # HIGHEST: grid coords feed gathers — bf16 MXU rounding would shift pixels
    grid = jnp.einsum("...k,njk->n...j", hom, theta,
                      precision=lax.Precision.HIGHEST)      # [N,(D,)H,W,nd]
    return grid


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(coord, size, align_corners):
    if align_corners:
        if size == 1:
            return jnp.zeros_like(coord)
        span = 2.0 * (size - 1)
        coord = jnp.abs(coord) % span
        return jnp.where(coord > size - 1, span - coord, coord)
    span = 2.0 * size
    coord = jnp.abs(coord + 0.5) % span
    coord = jnp.where(coord > size, span - coord, coord) - 0.5
    return jnp.clip(coord, 0, size - 1)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Sample ``x`` at ``grid`` locations (ref functional/vision.py).

    4-D: x [N,C,H,W], grid [N,Hg,Wg,2] (x,y in [-1,1]) → [N,C,Hg,Wg].
    5-D: x [N,C,D,H,W], grid [N,Dg,Hg,Wg,3] → [N,C,Dg,Hg,Wg].
    Pure gather formulation — XLA lowers to vectorized dynamic-gathers; no
    scatter, so it fuses into surrounding elementwise work.
    """
    spatial = x.shape[2:]
    nd = len(spatial)
    assert grid.shape[-1] == nd, "grid last dim must match spatial rank"
    cdtype = jnp.promote_types(x.dtype, jnp.float32)
    g = grid.astype(cdtype)

    # per-axis pixel coords; grid order is (x, y[, z]) → spatial axes reversed
    coords = []
    for i in range(nd):
        size = spatial[nd - 1 - i]            # x ↔ last spatial axis
        c = _unnormalize(g[..., i], size, align_corners)
        if padding_mode == "reflection":
            c = _reflect(c, size, align_corners)
        elif padding_mode == "border":
            c = jnp.clip(c, 0, size - 1)
        coords.append(c)
    coords = coords[::-1]  # now ordered like spatial axes ((z,)y,x)

    x_cl = jnp.moveaxis(x, 1, -1)  # [N, *spatial, C] — channels-last gather

    def gather(idx_list, valid):
        # idx_list: per-spatial-axis integer index arrays [N, *out_spatial]
        n = x.shape[0]
        bidx = jnp.arange(n).reshape((n,) + (1,) * (idx_list[0].ndim - 1))
        clipped = [jnp.clip(ix, 0, s - 1) for ix, s in zip(idx_list, spatial)]
        out = x_cl[(bidx,) + tuple(clipped)]   # [N, *out_spatial, C]
        if valid is not None:
            out = jnp.where(valid[..., None], out, 0)
        return out

    if mode == "nearest":
        idx = [jnp.round(c).astype(jnp.int32) for c in coords]
        valid = None
        if padding_mode == "zeros":
            valid = jnp.ones(idx[0].shape, bool)
            for ix, s in zip(idx, spatial):
                valid &= (ix >= 0) & (ix <= s - 1)
        out = gather(idx, valid)
        return jnp.moveaxis(out, -1, 1).astype(x.dtype)

    # bilinear / trilinear: 2^nd corner gathers with product weights
    lo = [jnp.floor(c).astype(jnp.int32) for c in coords]
    frac = [c - l for c, l in zip(coords, lo)]
    out = 0.0
    for corner in range(1 << nd):
        idx, w = [], 1.0
        for axis in range(nd):
            hi_bit = (corner >> axis) & 1
            ix = lo[axis] + hi_bit
            idx.append(ix)
            w = w * (frac[axis] if hi_bit else (1.0 - frac[axis]))
        valid = None
        if padding_mode == "zeros":
            valid = jnp.ones(idx[0].shape, bool)
            for ix, s in zip(idx, spatial):
                valid &= (ix >= 0) & (ix <= s - 1)
        out = out + gather(idx, valid) * w[..., None].astype(cdtype)
    return jnp.moveaxis(out, -1, 1).astype(x.dtype)


# -- beam-search utilities (ref functional/extension.py) ---------------------

def gather_tree(ids, parents):
    """Reconstruct full beam sequences from per-step ids + parent pointers
    (ref ``paddle.nn.functional.gather_tree``). Shapes: [T, B, beam].

    Lowered as a single reverse ``lax.scan`` — the backtrace is sequential
    by nature but stays on-device (no host loop)."""
    def step(beam, xs):
        idt, part = xs
        out = jnp.take_along_axis(idt, beam, axis=-1)
        return jnp.take_along_axis(part, beam, axis=-1), out

    init = jnp.broadcast_to(jnp.arange(ids.shape[2], dtype=ids.dtype),
                            ids.shape[1:])
    _, outs = lax.scan(step, init, (ids[::-1], parents[::-1]))
    return outs[::-1]


# -- extra losses (ref functional/loss.py) -----------------------------------

def dice_loss(input, label, epsilon=1e-5):
    """Ref loss.py:dice_loss — input is post-softmax probs [N, ..., C],
    label int [N, ..., 1]."""
    label = jnp.squeeze(label, axis=-1)
    one_hot = jax.nn.one_hot(label, input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * one_hot, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(one_hot, axis=reduce_dims)
    dice = (2.0 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1.0 - dice)


def log_loss(input, label, epsilon=1e-4):
    """Ref loss.py:log_loss — binary cross entropy on probabilities."""
    return (-label * jnp.log(input + epsilon)
            - (1.0 - label) * jnp.log(1.0 - input + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Ref loss.py:npair_loss — softmax cross entropy over the anchor x
    positive similarity matrix plus an L2 pull on the embeddings."""
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1))
                    + jnp.mean(jnp.sum(positive * positive, axis=1))) * 0.25
    sim = anchor @ positive.T  # [B, B]
    labels = labels.reshape(-1)
    same = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    targets = same / jnp.sum(same, axis=1, keepdims=True)
    ce = -jnp.sum(targets * jax.nn.log_softmax(sim, axis=1), axis=1)
    return jnp.mean(ce) + reg


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM temporal shift (ref phi temporal_shift kernel): within each clip
    of ``seg_num`` frames, the first ``shift_ratio`` of channels shift one
    frame back, the next block one frame forward. Pure slicing/padding —
    XLA fuses it into the surrounding convs."""
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate([xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate([jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (ref loss.py:margin_cross_entropy):
    cos(m1*theta + m2) - m3 on the target class, then scaled CE. For the
    tensor-parallel sharded-classes variant use
    ``paddle_tpu.distributed.tensor_parallel.parallel_cross_entropy``."""
    cos = jnp.clip(logits, -1.0, 1.0)
    theta = jnp.arccos(cos)
    one_hot = jax.nn.one_hot(label, logits.shape[-1], dtype=logits.dtype)
    target_cos = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(one_hot > 0, target_cos, cos) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(one_hot * logp, axis=-1)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


# -- generic pad + remaining functional gap-fill -----------------------------

def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """Ref functional/common.py:pad. ``pad`` pairs apply to the LAST dims
    first ([l, r] -> last dim; [l, r, t, b] -> last two dims, ...); when
    len(pad) == 2*ndim it is per-dim pairs in dim order like jnp.pad."""
    from paddle_tpu.tensor import pad as _tensor_pad
    return _tensor_pad(x, list(pad), mode=mode, value=value,
                       data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW"):
    l, r, t, b = _norm_tuple(padding, 4)
    if data_format == "NCHW":
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r)))
    return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))


def _adaptive_max_along(x, axis, out_size):
    size = x.shape[axis]
    if size % out_size == 0:
        shape = list(x.shape)
        shape[axis:axis + 1] = [out_size, size // out_size]
        return x.reshape(shape).max(axis=axis + 1)
    m = _adaptive_avg_matrix(size, out_size, jnp.float32) > 0  # [out, in]
    xm = jnp.moveaxis(x, axis, -1)
    big = jnp.where(m.reshape((1,) * (xm.ndim - 1) + m.shape),
                    xm[..., None, :], -jnp.inf)
    return jnp.moveaxis(big.max(axis=-1).astype(x.dtype), -1, axis)


def adaptive_max_pool3d(x, output_size):
    out = _norm_tuple(output_size, 3)
    for axis, o in zip((2, 3, 4), out):
        x = _adaptive_max_along(x, axis, o)
    return x


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    """Legacy fused CE entry point (ref loss.py:softmax_with_cross_entropy);
    label holds class ids [..., 1] unless soft_label."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label
        squeeze = lab.ndim == logits.ndim
        if squeeze:
            lab = jnp.squeeze(lab, axis=axis)
        valid = lab != ignore_index
        lab_safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lab_safe, axis), axis=axis)
        loss = jnp.where(jnp.expand_dims(valid, axis), -picked, 0.0)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    """Ref loss.py:triplet_margin_with_distance_loss — triplet loss with a
    caller-supplied distance (default L2)."""
    dist = distance_function or (
        lambda a, b: jnp.linalg.norm(a - b, axis=-1))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(d_pos - d_neg + margin, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None):
    """Hierarchical sigmoid loss (ref loss.py:hsigmoid_loss). Default tree:
    complete binary heap over classes — leaf of class c sits at heap node
    c + num_classes - 1; internal node n scores sigmoid(x . w_n + b_n) and
    the BCE target is whether the path descends to the right child. Custom
    trees come in via (path_table, path_code) like the reference.

    The path walk is a static ceil(log2(C))-iteration loop of heap
    arithmetic — jit-friendly, no host lookups.
    """
    x = input
    b, dim = x.shape
    label = jnp.reshape(label, (-1,))  # accept [N] or the documented [N, 1]
    if path_table is not None:
        codes = path_code
        nodes = path_table
        valid = nodes >= 0
        nodes = jnp.maximum(nodes, 0)
    else:
        depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))) + 1)
        leaf = label + num_classes - 1  # heap id of the class leaf
        node_list, code_list, valid_list = [], [], []
        cur = leaf
        for _ in range(depth):
            parent = (cur - 1) // 2
            is_right = (cur % 2) == 0  # right children are even heap ids
            above_root = cur > 0
            node_list.append(jnp.where(above_root, parent, 0))
            code_list.append(jnp.where(above_root, is_right, False))
            valid_list.append(above_root)
            cur = jnp.where(above_root, parent, 0)
        nodes = jnp.stack(node_list, axis=-1)    # [B, depth]
        codes = jnp.stack(code_list, axis=-1)
        valid = jnp.stack(valid_list, axis=-1)
    w = jnp.take(weight, nodes, axis=0)          # [B, depth, dim]
    logits = jnp.einsum("bd,btd->bt", x, w)
    if bias is not None:
        logits = logits + jnp.take(bias.reshape(-1), nodes, axis=0)
    target = codes.astype(logits.dtype)
    bce = jnp.maximum(logits, 0) - logits * target + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    return jnp.sum(jnp.where(valid, bce, 0.0), axis=-1, keepdims=True)
