"""Transformer layers (ref: ``python/paddle/nn/layer/transformer.py``).

MultiHeadAttention keeps the reference's API (embed_dim, num_heads, separate
q/k/v projections, optional cached decoding) but computes through the fused
attention dispatch (Pallas flash on TPU). Adds GQA (num_kv_heads) which the
reference exposes via fused_multi_transformer.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, LayerList, LayerNorm, Linear


class MultiHeadAttention(Module):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 num_kv_heads=None, bias_attr=True, dtype=None):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.head_dim = embed_dim // num_heads
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        kv_out = self.num_kv_heads * self.head_dim
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.k_proj = Linear(kdim, kv_out, bias_attr=bias_attr, dtype=dtype)
        self.v_proj = Linear(vdim, kv_out, bias_attr=bias_attr, dtype=dtype)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.dropout = dropout

    def __call__(self, query, key=None, value=None, attn_mask=None, is_causal=False,
                 cache=None, rng=None, kv_lens=None):
        key = query if key is None else key
        value = key if value is None else value
        b, sq, _ = query.shape
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, key.shape[1], self.num_kv_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, value.shape[1], self.num_kv_heads, self.head_dim)
        new_cache = None
        if cache is not None:
            k, v, new_cache = cache.update(k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            is_causal=is_causal, training=self.training, rng=rng,
            kv_lens=kv_lens)
        out = self.out_proj(out.reshape(b, sq, self.embed_dim))
        return (out, new_cache) if cache is not None else out


class TransformerEncoderLayer(Module):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="gelu", normalize_before=False, dtype=None):
        super().__init__()
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout, dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = activation
        self.normalize_before = normalize_before

    def _ff(self, x):
        act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu}[self.activation]
        return self.linear2(act(self.linear1(x)))

    def __call__(self, src, src_mask=None, rng=None):
        r1, r2 = (None, None) if rng is None else tuple(jax.random.split(rng))
        residual = src
        x = self.norm1(src) if self.normalize_before else src
        x = self.self_attn(x, attn_mask=src_mask, rng=r1)
        x = residual + self.dropout1(x, rng=r1)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self._ff(y)
        x = residual + self.dropout2(y, rng=r2)
        if not self.normalize_before:
            x = self.norm2(x)
        return x


class TransformerEncoder(Module):
    def __init__(self, layer_fn, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([layer_fn() for _ in range(num_layers)])
        self.norm = norm

    def __call__(self, src, src_mask=None, rng=None):
        x = src
        for i, layer in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = layer(x, src_mask=src_mask, rng=sub)
        if self.norm is not None:
            x = self.norm(x)
        return x


class TransformerDecoderLayer(Module):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="gelu", normalize_before=True, dtype=None):
        super().__init__()
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout, dtype=dtype)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=dropout, dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.norm3 = LayerNorm(d_model, dtype=dtype)
        self.dropout_p = dropout
        self.activation = activation
        self.normalize_before = normalize_before

    def __call__(self, tgt, memory, tgt_mask=None, memory_mask=None, rng=None):
        r = (None,) * 3 if rng is None else tuple(jax.random.split(rng, 3))
        x = tgt
        h = self.norm1(x) if self.normalize_before else x
        h = self.self_attn(h, attn_mask=tgt_mask, is_causal=tgt_mask is None, rng=r[0])
        x = x + F.dropout(h, self.dropout_p, self.training, rng=r[0])
        if not self.normalize_before:
            x = self.norm1(x)
        h = self.norm2(x) if self.normalize_before else x
        h = self.cross_attn(h, key=memory, attn_mask=memory_mask, rng=r[1])
        x = x + F.dropout(h, self.dropout_p, self.training, rng=r[1])
        if not self.normalize_before:
            x = self.norm2(x)
        h = self.norm3(x) if self.normalize_before else x
        act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu}[self.activation]
        h = self.linear2(act(self.linear1(h)))
        x = x + F.dropout(h, self.dropout_p, self.training, rng=r[2])
        if not self.normalize_before:
            x = self.norm3(x)
        return x


class TransformerDecoder(Module):
    """Stack of decoder layers (ref transformer.py:TransformerDecoder)."""

    def __init__(self, layer_fn, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([layer_fn() for _ in range(num_layers)])
        self.norm = norm

    def __call__(self, tgt, memory, tgt_mask=None, memory_mask=None, rng=None):
        x = tgt
        for i, layer in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = layer(x, memory, tgt_mask=tgt_mask, memory_mask=memory_mask, rng=sub)
        if self.norm is not None:
            x = self.norm(x)
        return x


class Transformer(Module):
    """Full encoder-decoder Transformer (ref transformer.py:Transformer).

    Keeps the reference constructor signature; ``custom_encoder`` /
    ``custom_decoder`` swap in user stacks. ``forward(src, tgt, ...)``
    returns decoder output [B, T_tgt, d_model].
    """

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", normalize_before=False,
                 custom_encoder=None, custom_decoder=None, dtype=None):
        super().__init__()
        self.d_model, self.nhead = d_model, nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            self.encoder = TransformerEncoder(
                lambda: TransformerEncoderLayer(
                    d_model, nhead, dim_feedforward, dropout, activation,
                    normalize_before, dtype=dtype),
                num_encoder_layers,
                norm=LayerNorm(d_model, dtype=dtype) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            self.decoder = TransformerDecoder(
                lambda: TransformerDecoderLayer(
                    d_model, nhead, dim_feedforward, dropout, activation,
                    normalize_before, dtype=dtype),
                num_decoder_layers,
                norm=LayerNorm(d_model, dtype=dtype) if normalize_before else None)

    def __call__(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None,
                 rng=None):
        r1, r2 = (None, None) if rng is None else tuple(jax.random.split(rng))
        memory = self.encoder(src, src_mask=src_mask, rng=r1)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask, rng=r2)

    @staticmethod
    def generate_square_subsequent_mask(length):
        """Additive causal mask: 0 on/below diagonal, -inf above."""
        row = jnp.arange(length)[:, None]
        col = jnp.arange(length)[None, :]
        return jnp.where(col <= row, 0.0, -jnp.inf).astype(jnp.float32)
