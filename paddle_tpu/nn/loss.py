"""Loss layer classes (ref: ``python/paddle/nn/layer/loss.py``)."""
from __future__ import annotations

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F


class CrossEntropyLoss(Module):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, label_smoothing=0.0, axis=-1):
        super().__init__()
        self.weight = weight
        self.ignore_index, self.reduction = ignore_index, reduction
        self.soft_label, self.label_smoothing, self.axis = soft_label, label_smoothing, axis

    def __call__(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.label_smoothing)


class MSELoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def __call__(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def __call__(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Module):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def __call__(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class NLLLoss(Module):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean"):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def __call__(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Module):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def __call__(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Module):
    def __init__(self, weight=None, reduction="mean", pos_weight=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def __call__(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class KLDivLoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def __call__(self, input, label):
        return F.kl_div(input, label, self.reduction)


class CosineEmbeddingLoss(Module):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def __call__(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class MarginRankingLoss(Module):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def __call__(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class TripletMarginLoss(Module):
    def __init__(self, margin=1.0, p=2.0, reduction="mean"):
        super().__init__()
        self.margin, self.p, self.reduction = margin, p, reduction

    def __call__(self, anchor, positive, negative):
        return F.triplet_margin_loss(anchor, positive, negative, self.margin,
                                     self.p, self.reduction)


class HingeEmbeddingLoss(Module):
    def __init__(self, margin=1.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def __call__(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class SoftMarginLoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def __call__(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Module):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def __call__(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight, self.reduction)


class MultiMarginLoss(Module):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean"):
        super().__init__()
        self.p, self.margin, self.weight, self.reduction = p, margin, weight, reduction

    def __call__(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class PoissonNLLLoss(Module):
    def __init__(self, log_input=True, full=False, epsilon=1e-8, reduction="mean"):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def __call__(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class GaussianNLLLoss(Module):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean"):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def __call__(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class CTCLoss(Module):
    """Ref: paddle.nn.CTCLoss (warpctc). Takes log-softmax-normalised
    log_probs of shape [T, B, C]."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def __call__(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)


class TripletMarginWithDistanceLoss(Module):
    """Ref loss.py:TripletMarginWithDistanceLoss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean"):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def __call__(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class HSigmoidLoss(Module):
    """Hierarchical sigmoid (ref loss.py:HSigmoidLoss): owns the internal-
    node weight table [num_classes - 1, dim] (+bias)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=True, is_custom=False, dtype=None):
        super().__init__()
        from paddle_tpu.core.dtypes import get_default_dtype
        from paddle_tpu.nn import initializer as I
        dtype = dtype or get_default_dtype()
        n_nodes = num_classes - 1
        self.weight = I.XavierNormal()((n_nodes, feature_size), dtype)
        self.bias = I.Constant(0.0)((n_nodes, 1), dtype) if bias_attr else None
        self.num_classes = num_classes
        self.is_custom = is_custom

    def __call__(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)
