"""Layer classes (ref: ``python/paddle/nn/layer/common.py``, ``conv.py``,
``norm.py``, ``pooling.py``, ``activation.py``, ``container.py``).

Every layer is a pytree Module: construction materialises parameters eagerly
(reference dygraph behaviour) from the global seeded RNG; calls are pure.
Layers with randomness (Dropout) take an optional ``rng=`` keyword — inside
``jit`` you must pass it (the trainer threads an RngStream); in eager mode it
falls back to the global generator.
"""
from __future__ import annotations

import inspect
import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I

__all__ = [
    "Linear", "Identity", "Bilinear", "Embedding", "Dropout", "Dropout2D",
    "AlphaDropout", "Flatten", "Pad1D", "Pad2D", "Upsample", "PixelShuffle",
    "Sequential", "LayerList", "LayerDict",
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
    "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
    "BatchNorm3D", "GroupNorm", "InstanceNorm2D", "LocalResponseNorm",
    "MaxPool1D", "MaxPool2D", "AvgPool1D", "AvgPool2D", "AdaptiveAvgPool2D",
    "AdaptiveMaxPool2D",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Mish", "Sigmoid", "Tanh",
    "Softmax", "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU", "Hardswish",
    "Hardsigmoid", "Hardtanh", "PReLU", "Softplus", "Softshrink", "Hardshrink",
    "Softsign", "Tanhshrink", "ThresholdedReLU", "Maxout", "GLU", "RReLU",
    "Pad3D", "ZeroPad2D", "Unflatten", "Unfold", "Fold", "PixelUnshuffle",
    "ChannelShuffle", "CosineSimilarity", "PairwiseDistance", "InstanceNorm1D",
    "InstanceNorm3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "SpectralNorm",
    "MaxPool3D", "AvgPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "Softmax2D", "Dropout3D",
    "Conv1DTranspose", "Conv3DTranspose", "AdaptiveMaxPool3D", "LogSigmoid",
    "ParameterList", "SyncBatchNorm", "UpsamplingNearest2D",
    "UpsamplingBilinear2D",
]


def _maybe_rng_call(layer, x, rng):
    """Call `layer(x)` passing rng= only if the layer accepts it."""
    sig = getattr(type(layer), "_accepts_rng", None)
    if sig is None:
        params = inspect.signature(type(layer).__call__).parameters
        sig = "rng" in params
        type(layer)._accepts_rng = sig
    return layer(x, rng=rng) if sig else layer(x)


# -- core layers ------------------------------------------------------------

class Linear(Module):
    """y = x @ W + b, W: [in, out] (reference layout, python/paddle/nn/layer/common.py:Linear)."""

    def __init__(self, in_features: int, out_features: int, bias_attr=True,
                 weight_init: Optional[I.Initializer] = None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = I.default_weight_init(weight_init, I.XavierNormal())
        self.weight = init((in_features, out_features), dtype)
        self.bias = (I.default_bias_init(I.Constant(0.0))((out_features,), dtype)
                     if bias_attr else None)
        self.in_features, self.out_features = in_features, out_features

    def __call__(self, x):
        return F.linear(x, self.weight, self.bias)


class Identity(Module):
    def __call__(self, x):
        return x


class Bilinear(Module):
    def __init__(self, in1_features, in2_features, out_features, bias_attr=True):
        super().__init__()
        dtype = get_default_dtype()
        bound = 1.0 / math.sqrt(in1_features)
        self.weight = I.Uniform(-bound, bound)((out_features, in1_features, in2_features), dtype)
        self.bias = I.Constant(0.0)((out_features,), dtype) if bias_attr else None

    def __call__(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Embedding(Module):
    """Ref: python/paddle/nn/layer/common.py:Embedding. Dense gather on TPU
    (no sparse grads — XLA scatters the cotangent)."""

    def __init__(self, num_embeddings: int, embedding_dim: int, padding_idx=None,
                 weight_init: Optional[I.Initializer] = None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = I.default_weight_init(weight_init, I.Normal(0.0, 1.0))
        self.weight = init((num_embeddings, embedding_dim), dtype)
        self.padding_idx = padding_idx
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim

    def __call__(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


class Dropout(Module):
    def __init__(self, p=0.5, axis=None):
        super().__init__()
        self.p, self.axis = p, axis

    def __call__(self, x, rng=None):
        return F.dropout(x, self.p, training=self.training, rng=rng, axis=self.axis)


class Dropout2D(Module):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def __call__(self, x, rng=None):
        return F.dropout2d(x, self.p, training=self.training, rng=rng)


class Dropout3D(Module):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def __call__(self, x, rng=None):
        return F.dropout3d(x, self.p, training=self.training, rng=rng)


class Softmax2D(Module):
    """Softmax over the channel axis of NCHW input (ref activation.py:Softmax2D)."""

    def __call__(self, x):
        return F.softmax(x, axis=-3)


class AlphaDropout(Module):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def __call__(self, x, rng=None):
        return F.alpha_dropout(x, self.p, training=self.training, rng=rng)


class Flatten(Module):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def __call__(self, x):
        from paddle_tpu.tensor import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Module):
    _nd = 1

    def __init__(self, padding, mode="constant", value=0.0):
        super().__init__()
        if isinstance(padding, int):
            padding = (padding,) * (2 * self._nd)
        self.padding, self.mode, self.value = tuple(padding), mode, value

    def __call__(self, x):
        from paddle_tpu.tensor import pad
        return pad(x, list(self.padding), mode=self.mode, value=self.value)


class Pad2D(Pad1D):
    _nd = 2


class Upsample(Module):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners

    def __call__(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners)


class PixelShuffle(Module):
    def __init__(self, upscale_factor):
        super().__init__()
        self.upscale_factor = upscale_factor

    def __call__(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


# -- containers (ref container.py) ------------------------------------------

class Sequential(Module):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = tuple(layers[0])
        self.layers = list(layers)

    def __call__(self, x, rng=None):
        for i, layer in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = _maybe_rng_call(layer, x, sub)
        return x

    def __getitem__(self, idx):
        return self.layers[idx]

    def __len__(self):
        return len(self.layers)

    def append(self, layer):
        self.layers.append(layer)


class LayerList(Module):
    def __init__(self, layers=()):
        super().__init__()
        self.layers = list(layers)

    def __getitem__(self, idx):
        return self.layers[idx]

    def __setitem__(self, idx, layer):
        self.layers[idx] = layer

    def __len__(self):
        return len(self.layers)

    def __iter__(self):
        return iter(self.layers)

    def append(self, layer):
        self.layers.append(layer)

    def extend(self, layers):
        self.layers.extend(layers)


class LayerDict(Module):
    def __init__(self, layers=None):
        super().__init__()
        self.layers = dict(layers or {})

    def __getitem__(self, k):
        return self.layers[k]

    def __setitem__(self, k, v):
        self.layers[k] = v

    def keys(self):
        return self.layers.keys()

    def values(self):
        return self.layers.values()

    def items(self):
        return self.layers.items()


# -- conv layers (ref conv.py) ----------------------------------------------

class _ConvNd(Module):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=True,
                 weight_init=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        k = (kernel_size,) * nd if isinstance(kernel_size, int) else tuple(kernel_size)
        shape = (out_channels, in_channels // groups) + k
        init = I.default_weight_init(weight_init, I.KaimingUniform())
        self.weight = init(shape, dtype)
        self.bias = (I.default_bias_init(I.Constant(0.0))((out_channels,), dtype)
                     if bias_attr else None)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.in_channels, self.out_channels = in_channels, out_channels


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(1, in_channels, out_channels, kernel_size, **kw)

    def __call__(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(2, in_channels, out_channels, kernel_size, **kw)

    def __call__(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(3, in_channels, out_channels, kernel_size, **kw)

    def __call__(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups)


class Conv2DTranspose(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, bias_attr=True, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.weight = I.default_weight_init(None, I.KaimingUniform())(
            (in_channels, out_channels // groups) + k, dtype)
        self.bias = (I.default_bias_init(I.Constant(0.0))((out_channels,), dtype)
                     if bias_attr else None)
        self.stride, self.padding, self.output_padding = stride, padding, output_padding
        self.dilation, self.groups = dilation, groups

    def __call__(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.dilation, self.groups)


# -- norm layers (ref norm.py) ----------------------------------------------

class LayerNorm(Module):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=True,
                 bias_attr=True, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        shape = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
        self.weight = I.Constant(1.0)(shape, dtype) if weight_attr else None
        self.bias = I.Constant(0.0)(shape, dtype) if bias_attr else None
        self.normalized_shape, self.epsilon = shape, epsilon

    def __call__(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)


class RMSNorm(Module):
    """Ref: paddle.incubate.nn.FusedRMSNorm / LLaMA RMSNorm."""

    def __init__(self, hidden_size, epsilon=1e-6, dtype=None):
        super().__init__()
        self.weight = I.Constant(1.0)((hidden_size,), dtype or get_default_dtype())
        self.epsilon = epsilon

    def __call__(self, x):
        from paddle_tpu.ops import fused_rms_norm
        return fused_rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Module):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.weight = I.Constant(1.0)((num_features,), dtype)
        self.bias = I.Constant(0.0)((num_features,), dtype)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))
        self.momentum, self.epsilon = momentum, epsilon

    def __call__(self, x):
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon)
        if self.training:
            # eager-mode stat update; under jit use functional batch_norm directly
            try:
                object.__setattr__(self, "_mean", new_mean)
                object.__setattr__(self, "_variance", new_var)
            except Exception:
                pass
        return out


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class GroupNorm(Module):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.weight = I.Constant(1.0)((num_channels,), dtype)
        self.bias = I.Constant(0.0)((num_channels,), dtype)
        self.num_groups, self.epsilon = num_groups, epsilon

    def __call__(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon)


class InstanceNorm2D(Module):
    def __init__(self, num_features, epsilon=1e-5, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.weight = I.Constant(1.0)((num_features,), dtype)
        self.bias = I.Constant(0.0)((num_features,), dtype)
        self.epsilon = epsilon

    def __call__(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon)


class LocalResponseNorm(Module):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def __call__(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


# -- pooling layers ---------------------------------------------------------

class MaxPool2D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.return_mask = return_mask

    def __call__(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask)


class MaxPool3D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.return_mask = return_mask

    def __call__(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask)


class AvgPool3D(Module):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def __call__(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding)


class MaxUnPool1D(Module):
    """Inverse max-pool scatter (ref pooling.py:MaxUnPool1D)."""

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def __call__(self, x, indices, output_size=None):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size)


class MaxUnPool2D(MaxUnPool1D):
    def __call__(self, x, indices, output_size=None):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size)


class MaxUnPool3D(MaxUnPool1D):
    def __call__(self, x, indices, output_size=None):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size)


class AvgPool2D(Module):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def __call__(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding)


class MaxPool1D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.return_mask = return_mask

    def __call__(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask)


class AvgPool1D(Module):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def __call__(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool2D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


# -- activation layers ------------------------------------------------------

def _act_layer(name, fn, **defaults):
    def __init__(self, **kw):
        Module.__init__(self)
        for k, v in defaults.items():
            setattr(self, k, kw.get(k, v))

    def __call__(self, x):
        kw = {k: getattr(self, k) for k in defaults}
        return fn(x, **kw)

    return type(name, (Module,), {"__init__": __init__, "__call__": __call__})


ReLU = _act_layer("ReLU", lambda x: F.relu(x))
ReLU6 = _act_layer("ReLU6", lambda x: F.relu6(x))
GELU = _act_layer("GELU", F.gelu, approximate=False)
SiLU = _act_layer("SiLU", lambda x: F.silu(x))
Swish = _act_layer("Swish", lambda x: F.silu(x))
Mish = _act_layer("Mish", lambda x: F.mish(x))
Sigmoid = _act_layer("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _act_layer("Tanh", lambda x: F.tanh(x))
Softmax = _act_layer("Softmax", F.softmax, axis=-1)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, axis=-1)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _act_layer("ELU", lambda x, alpha=1.0: F.elu(x, alpha), alpha=1.0)
SELU = _act_layer("SELU", lambda x: F.selu(x))
CELU = _act_layer("CELU", lambda x, alpha=1.0: F.celu(x, alpha), alpha=1.0)
Hardswish = _act_layer("Hardswish", lambda x: F.hardswish(x))
Hardsigmoid = _act_layer("Hardsigmoid", lambda x: F.hardsigmoid(x))
Hardtanh = _act_layer("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Softplus = _act_layer("Softplus", lambda x: F.softplus(x))
Softshrink = _act_layer("Softshrink", F.softshrink, threshold=0.5)
Hardshrink = _act_layer("Hardshrink", F.hardshrink, threshold=0.5)
Softsign = _act_layer("Softsign", lambda x: F.softsign(x))
Tanhshrink = _act_layer("Tanhshrink", lambda x: F.tanhshrink(x))
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu, threshold=1.0)
Maxout = _act_layer("Maxout", F.maxout, groups=2, axis=1)
GLU = _act_layer("GLU", F.glu, axis=-1)
LogSigmoid = _act_layer("LogSigmoid", lambda x: F.log_sigmoid(x))


class PReLU(Module):
    def __init__(self, num_parameters=1, init=0.25, dtype=None):
        super().__init__()
        self.weight = I.Constant(init)((num_parameters,), dtype or get_default_dtype())

    def __call__(self, x):
        return F.prelu(x, self.weight)


# -- widened layer surface (ref common.py / norm.py / vision.py) -------------

class Pad3D(Pad1D):
    _nd = 3


class ZeroPad2D(Pad1D):
    _nd = 2

    def __init__(self, padding):
        super().__init__(padding, mode="constant", value=0.0)


class Unflatten(Module):
    def __init__(self, axis, shape):
        super().__init__()
        self.axis, self.shape = axis, tuple(shape)

    def __call__(self, x):
        ax = self.axis % x.ndim
        return x.reshape(x.shape[:ax] + self.shape + x.shape[ax + 1:])


class Unfold(Module):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def __call__(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Module):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings, self.dilations = strides, paddings, dilations

    def __call__(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class PixelUnshuffle(Module):
    def __init__(self, downscale_factor):
        super().__init__()
        self.downscale_factor = downscale_factor

    def __call__(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor)


class ChannelShuffle(Module):
    def __init__(self, groups):
        super().__init__()
        self.groups = groups

    def __call__(self, x):
        return F.channel_shuffle(x, self.groups)


class CosineSimilarity(Module):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def __call__(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Module):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def __call__(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class InstanceNorm1D(InstanceNorm2D):
    pass


class InstanceNorm3D(InstanceNorm2D):
    pass


class AdaptiveAvgPool1D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class RReLU(Module):
    """Randomised leaky ReLU (ref activation.py:RReLU). In eval mode uses the
    mean slope; in train mode samples slopes per element from U(lower, upper)."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0):
        super().__init__()
        self.lower, self.upper = lower, upper

    def __call__(self, x, rng=None):
        if not self.training or rng is None:
            return F.leaky_relu(x, (self.lower + self.upper) / 2)
        slope = jax.random.uniform(rng, x.shape, jnp.float32,
                                   self.lower, self.upper).astype(x.dtype)
        return jnp.where(x >= 0, x, slope * x)


class SpectralNorm(Module):
    """Ref: paddle.nn.SpectralNorm — forward(weight) returns weight / sigma
    where sigma is estimated by power iteration. Stateless under jit: the
    u/v vectors are buffers updated eagerly, frozen inside traces."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        # u/v kept fp32 regardless of weight dtype: power iteration is
        # norm-sensitive and the vectors are tiny
        self.register_buffer("weight_u", I.Normal(0, 1)((h,), jnp.float32))
        self.register_buffer("weight_v", I.Normal(0, 1)((w,), jnp.float32))
        self.dim, self.power_iters, self.eps = dim, power_iters, eps

    def __call__(self, weight):
        mat = jnp.moveaxis(weight, self.dim, 0).reshape(weight.shape[self.dim], -1)
        mat = mat.astype(jnp.float32)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        # u/v are constants w.r.t. the gradient (reference no_grad buffers)
        u = lax.stop_gradient(u)
        v = lax.stop_gradient(v)
        # persist the iteration so repeated eager calls converge; under jit
        # u is a tracer and must not escape onto the module
        if not isinstance(u, jax.core.Tracer):
            object.__setattr__(self, "weight_u", u)
            object.__setattr__(self, "weight_v", v)
        sigma = u @ mat @ v
        return (weight / sigma.astype(weight.dtype))


class Conv1DTranspose(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 bias_attr=True, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        k = (kernel_size,) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.weight = I.default_weight_init(None, I.KaimingUniform())(
            (in_channels, out_channels // groups) + k, dtype)
        self.bias = (I.default_bias_init(I.Constant(0.0))((out_channels,), dtype)
                     if bias_attr else None)
        self.stride, self.padding, self.output_padding = stride, padding, output_padding
        self.dilation, self.groups = dilation, groups

    def __call__(self, x):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups)


class Conv3DTranspose(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 bias_attr=True, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.weight = I.default_weight_init(None, I.KaimingUniform())(
            (in_channels, out_channels // groups) + k, dtype)
        self.bias = (I.default_bias_init(I.Constant(0.0))((out_channels,), dtype)
                     if bias_attr else None)
        self.stride, self.padding, self.output_padding = stride, padding, output_padding
        self.dilation, self.groups = dilation, groups

    def __call__(self, x):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups)


class AdaptiveMaxPool3D(Module):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def __call__(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class ParameterList(Module):
    """Ref container.py:ParameterList — an indexable list of parameters."""

    def __init__(self, parameters=None):
        super().__init__()
        self.params = list(parameters) if parameters is not None else []

    def append(self, p):
        self.params.append(p)
        return self

    def __getitem__(self, i):
        return self.params[i]

    def __len__(self):
        return len(self.params)

    def __iter__(self):
        return iter(self.params)


class SyncBatchNorm(_BatchNormBase):
    """Ref norm.py:SyncBatchNorm. Under GSPMD the batch axes of a sharded
    activation are already reduced globally when this runs inside jit with
    sharding annotations (XLA inserts the cross-replica psum for the mean/
    var reductions), so the TPU implementation IS BatchNorm — kept as its
    own class for API parity and for convert_sync_batchnorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Swap every _BatchNormBase in a module tree for SyncBatchNorm."""
        def convert(value):
            if isinstance(value, _BatchNormBase) and not isinstance(value, cls):
                new = cls.__new__(cls)
                new.__dict__.update(value.__dict__)
                # fresh mutable containers — sharing them would let later
                # register_buffer/set_pspec mutate the original layer too
                new._buffers = set(value._buffers)
                new._pspecs = dict(value._pspecs)
                new._dyn_names = set(value._dyn_names)
                return new
            if isinstance(value, Module):
                for name, sub in list(vars(value).items()):
                    if name in ("_buffers", "_pspecs", "_dyn_names"):
                        continue
                    object.__setattr__(value, name, convert(sub))
                return value
            if isinstance(value, list):
                return [convert(v) for v in value]
            if isinstance(value, tuple):
                return tuple(convert(v) for v in value)
            if isinstance(value, dict):
                return {k: convert(v) for k, v in value.items()}
            return value

        return convert(layer)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="nearest")


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear",
                         align_corners=True)
