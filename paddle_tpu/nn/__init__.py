from paddle_tpu.core.module import Module as Layer  # reference name
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional, initializer
from paddle_tpu.nn.layers import *  # noqa: F401,F403
from paddle_tpu.nn.loss import (
    HSigmoidLoss,
    TripletMarginWithDistanceLoss,
    BCELoss,
    BCEWithLogitsLoss,
    CosineEmbeddingLoss,
    CrossEntropyLoss,
    CTCLoss,
    GaussianNLLLoss,
    HingeEmbeddingLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    MultiLabelSoftMarginLoss,
    MultiMarginLoss,
    NLLLoss,
    PoissonNLLLoss,
    SmoothL1Loss,
    SoftMarginLoss,
    TripletMarginLoss,
)
from paddle_tpu.nn.rnn import (
    GRU,
    RNN,
    BiRNN,
    _RNNCellBase as RNNCellBase,
    GRUCell,
    LSTM,
    LSTMCell,
    SimpleRNN,
    SimpleRNNCell,
)
from paddle_tpu.nn.transformer import (
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
