"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of the reference (roar090/Paddle), redesigned for XLA/JAX/Pallas.

Top-level namespace mirrors the reference: ``paddle_tpu.nn``,
``paddle_tpu.optimizer``, ``paddle_tpu.distributed`` (fleet),
``paddle_tpu.amp``, ``paddle_tpu.io``, ``paddle_tpu.vision`` plus tensor ops
re-exported at the root (``paddle_tpu.matmul`` etc. like ``paddle.matmul``).
"""
from __future__ import annotations

__version__ = "0.1.0"

from paddle_tpu import amp, callbacks, core, io, nn, ops, optimizer, utils
from paddle_tpu import (audio, autograd, distribution, fft, geometric, hub, incubate,
                        linalg, onnx, quantization, signal, sparse, static,
                        text)
from paddle_tpu.core import device
from paddle_tpu.summary_utils import flops, summary
from paddle_tpu.core.device import (
    device_count,
    get_device,
    is_tpu,
    set_device,
)
from paddle_tpu.core.dtypes import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from paddle_tpu.core.random import RngStream, next_key, seed
from paddle_tpu.core.module import Module, combine, partition_trainable, value_and_grad
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu import jit as jit_module
from paddle_tpu.jit import (
    to_static,
    no_grad,
    grad,
    set_grad_enabled,
    is_grad_enabled,
)
from paddle_tpu.train.checkpoint import load, save

jit = jit_module.jit
# paddle-style namespace access (paddle.jit.save/load/to_static) — the `jit`
# name is the callable, with the module surface attached as attributes
jit.save = jit_module.save
jit.load = jit_module.load
jit.to_static = jit_module.to_static
jit.InputSpec = jit_module.InputSpec


def __getattr__(name):
    # lazy heavy subpackages (distributed pulls mesh/jax topology; models the zoo)
    if name in ("distributed", "models", "train", "vision"):
        import importlib
        mod = importlib.import_module(f"paddle_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
