"""Regularizers (ref: ``python/paddle/regularizer.py`` — L1Decay, L2Decay).

Functional: produce a penalty term from a param tree; optimizers also accept
``weight_decay`` directly (the reference's common path)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


class L2Decay:
    def __init__(self, coeff=1e-4):
        self.coeff = coeff

    def __call__(self, params):
        tot = jnp.zeros((), jnp.float32)
        for leaf in jax.tree_util.tree_leaves(params):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
                tot = tot + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        return 0.5 * self.coeff * tot


class L1Decay:
    def __init__(self, coeff=1e-4):
        self.coeff = coeff

    def __call__(self, params):
        tot = jnp.zeros((), jnp.float32)
        for leaf in jax.tree_util.tree_leaves(params):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
                tot = tot + jnp.sum(jnp.abs(leaf.astype(jnp.float32)))
        return self.coeff * tot
