"""Degradation ladder + session durability (ISSUE 16).

The cluster can *sense* trouble (HealthEvaluator verdicts, the
goodput/waste ledger, KV stall counters) and *inject* it (the chaos
sites in ``utils/faults.py``), but until this module it could not
*react*. :class:`DegradationController` is the missing control loop: a
small host-side state machine, polled from the engine/Router gauge
sweep, that maps live pressure signals onto ordered, **reversible**
rungs of reduced service:

    =====  ==========================================================
    rung   effect (each rung includes the ones below it)
    =====  ==========================================================
    L0     full service — bit-identical to a build without the ladder
    L1     speculative decoding disabled (verify FLOPs back to decode)
    L2     chunked-prefill token budget shrunk (shorter head-of-line
           stalls, admission slows down)
    L3     best-effort tenants shed at admission (deferred, not
           dropped — composes with the deficit fair scheduler)
    L4     new sessions rejected with explicit backpressure
           (:class:`~paddle_tpu.serving.types.OverloadError`)
    =====  ==========================================================

Signals are **windowed**: each poll diffs counter totals and histogram
bucket counts against the previous poll's snapshot, so the ladder reads
"goodput ratio over the last window", not lifetime averages — a cluster
that thrashed an hour ago but is healthy now must come back to L0. An
empty window (no traffic) reads as healthy for the same reason.

Hysteresis is asymmetric by design: the ladder climbs to the worst
signal's target after ``up_patience`` consecutive polls (default 1 —
react fast), but descends ONE rung at a time after ``down_patience``
consecutive polls of calm (default 3 — recover slowly, so an
oscillating signal cannot flap service levels). Every transition sets
``serving_degrade_level``, increments
``serving_degrade_transitions_total{direction,to}``, and drops a
``serving.degrade`` flight-recorder event naming the signal that drove
it.

``PT_DEGRADE=0`` is the kill switch: checked on every poll *and* every
effect query, so flipping the env var mid-flight pins behaviour to L0
immediately. With the switch off — or simply at L0 — every effect
method returns the permissive answer and the serving path is
bit-identical to a build without the controller.

Feedback-loop note: the stock health rule ``serving_degrade_level``
(observability/health.py) reads the gauge this controller writes. Do
NOT hand that same evaluator to the controller's ``health=`` signal —
the rung would feed its own input and latch. The default is
``health=None`` for exactly this reason; pass a dedicated evaluator
with non-ladder rules if you want verdict-driven climbing.

:class:`SessionSnapshot` is the durability half: a periodic host-side
capture (prompt + generated ids + sampler RNG + adapter/grammar refs)
cheap enough to take every router step. The Router keeps the newest
snapshot per in-flight request; when a request's replica dies a
*second* time (the exactly-once requeue already spent), the snapshot
restores the session onto a surviving replica — replaying prefill
through the radix cache, waste billed as ``replay_prefill`` — instead
of failing the request with ``finish_reason="replica_death"``. For
greedy decoding the restored continuation is bit-identical to an
undisturbed run (the resumed prefill recomputes the same argmax path);
sampled (temperature > 0) sessions restore the RNG key advisorily but
share the engine-global PRNG stream, so only greedy output is promised
identical.
"""
from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.observability import METRICS
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.windows import WindowedReads
from paddle_tpu.serving.telemetry import (_DEGRADE_LEVEL,
                                          _DEGRADE_TRANSITIONS)

__all__ = ["DegradationController", "SessionSnapshot", "default_signals"]


# --------------------------------------------------------------- snapshots
@dataclass
class SessionSnapshot:
    """Host-side durability capture of one in-flight session. Small by
    construction — token ids and scalars only, never KV blocks: restore
    replays prefill (radix-cache hits make the replay cheap) rather
    than shipping cache state."""
    req_id: int
    prompt: object                    # 1-D int32 prompt ids (shared ref)
    tokens: Tuple[int, ...]           # generated ids at capture time
    session_id: object = None
    tenant_id: object = None
    adapter_id: object = None
    grammar: object = None            # automaton ref; state replays from ids
    rng: object = None                # engine PRNG key at capture (advisory)
    gen: int = 0                      # len(tokens) at capture
    captured_t: float = 0.0           # engine clock at capture

    def resume_ids(self) -> np.ndarray:
        """prompt + generated ids — the replay prefill input."""
        if not self.tokens:
            return np.asarray(self.prompt, np.int32)
        return np.concatenate([np.asarray(self.prompt, np.int32),
                               np.asarray(self.tokens, np.int32)])


# ----------------------------------------------------------- default signals
def default_signals(*, goodput_warn: float = 0.5, goodput_crit: float = 0.25,
                    goodput_min_tokens: int = 64,
                    queue_warn_s: float = 1.0, queue_crit_s: float = 5.0,
                    kv_util_floor: float = 0.97,
                    slo_burn: bool = False,
                    slo_burn_crit: float = 14.4) -> List[tuple]:
    """The stock signal set. Each signal is ``(name, fn)`` where ``fn``
    receives the controller and returns a target rung 0–4; the ladder
    steers toward the max over all signals. All reads are windowed
    through the controller's snapshot helpers, so targets describe the
    last poll interval, not process lifetime.

    ``slo_burn=True`` adds an OFF-BY-DEFAULT signal that targets L3
    (shed best-effort tenants) when any tenant's short-window
    ``serving_slo_burn_rate`` reaches ``slo_burn_crit`` (the tracker's
    fast-burn threshold). Caveat — this closes a feedback loop: the
    ladder's own mitigations (rejections at L4, shed tenants at L3)
    count against availability SLOs, so an aggressive threshold can
    latch the ladder high on the very errors it causes. That is why it
    ships disabled; enable it only with an availability objective whose
    budget tolerates the ladder's remedial rejections."""

    def health_sig(c) -> int:
        if c.health is None:
            return 0
        status = c.health.evaluate()["status"]
        return {"OK": 0, "WARN": 1, "CRIT": 3}.get(status, 0)

    def goodput_sig(c) -> int:
        ratio, volume = c.window_goodput()
        if volume < goodput_min_tokens or math.isnan(ratio):
            return 0
        if ratio < goodput_crit:
            return 3
        if ratio < goodput_warn:
            return 2
        return 0

    def queue_wait_sig(c) -> int:
        p95 = c.window_quantile("serving_queue_wait_seconds", 0.95)
        if math.isnan(p95):
            return 0
        if p95 >= queue_crit_s:
            return 4
        if p95 >= queue_warn_s:
            return 2
        return 0

    def kv_pressure_sig(c) -> int:
        util = c.gauge("serving_kv_block_utilization")
        stalls = c.window_counter("serving_kv_stall_total")
        return 2 if (util >= kv_util_floor and stalls > 0) else 0

    def slo_burn_sig(c) -> int:
        # max over tenant/objective series, not the sum — one tenant
        # burning hot should not be diluted by compliant neighbours
        inst = c.registry.get("serving_slo_burn_rate")
        if inst is None or not inst._series:
            return 0
        worst = max(cell[0] for cell in inst._series.values())
        return 3 if worst >= slo_burn_crit else 0

    sigs = [("health", health_sig), ("goodput", goodput_sig),
            ("queue_wait", queue_wait_sig), ("kv_pressure", kv_pressure_sig)]
    if slo_burn:
        sigs.append(("slo_burn", slo_burn_sig))
    return sigs


# ------------------------------------------------------------- controller
class DegradationController:
    """The ladder state machine. Construct one and hand it to the
    Router (``Router(..., degrade=ctrl)`` — shared by every replica and
    polled once per router step) or to a standalone engine
    (``LLMEngine(..., degrade=ctrl)`` — polled from its gauge sweep).
    Effect queries (:meth:`spec_enabled`, :meth:`prefill_budget`,
    :meth:`shed_best_effort`, :meth:`accepting_sessions`) are cheap and
    safe to call every tick."""

    MAX_LEVEL = 4

    def __init__(self, *, health=None, registry=None,
                 signals: Optional[Sequence[tuple]] = None,
                 up_patience: int = 1, down_patience: int = 3,
                 chunk_shrink: int = 4, clock: Callable[[], float] = None):
        if up_patience < 1 or down_patience < 1:
            raise ValueError("patience values must be >= 1")
        if chunk_shrink < 1:
            raise ValueError(f"chunk_shrink must be >= 1, got {chunk_shrink}")
        self.registry = registry if registry is not None else METRICS
        self.health = health
        self.signals = list(default_signals() if signals is None else signals)
        self.up_patience = up_patience
        self.down_patience = down_patience
        self.chunk_shrink = chunk_shrink
        self.clock = clock or time.monotonic
        self.level = 0
        self.peak_level = 0
        self.transitions: List[dict] = []     # host-side audit trail
        self.last_targets: dict = {}          # signal name -> last target
        # who polls: None = the owning engine's gauge sweep; a Router
        # claims the controller (owner=router) so N replica engines
        # sharing it don't each advance the hysteresis clocks per tick
        self.owner: object = None
        self._up_streak = 0
        self._down_streak = 0
        # windowed-read machinery (extracted to observability/windows.py
        # in ISSUE 19 so the SLO tracker shares it); this controller's
        # reader owns its own snapshot dict, so a co-resident SLOTracker
        # polling the same registry never steals the ladder's deltas
        self.windows = WindowedReads(self.registry)
        self._snap = self.windows._snap       # windowed-read snapshots
        _DEGRADE_LEVEL.set(0.0)

    # ------------------------------------------------------------ switches
    @staticmethod
    def enabled() -> bool:
        """``PT_DEGRADE=0`` kill switch, read per call so a mid-flight
        flip takes effect on the very next poll/effect query."""
        return os.environ.get("PT_DEGRADE", "1") != "0"

    @property
    def active_level(self) -> int:
        """The rung that actually governs behaviour (0 when killed)."""
        return self.level if self.enabled() else 0

    # ------------------------------------------------------------- effects
    def spec_enabled(self) -> bool:
        """L1+: speculative decoding off."""
        return self.active_level < 1

    def prefill_budget(self, full: int) -> int:
        """L2+: the chunked-prefill token budget, shrunk by
        ``chunk_shrink`` (never below one token)."""
        if self.active_level < 2:
            return full
        return max(1, int(full) // self.chunk_shrink)

    def shed_best_effort(self) -> bool:
        """L3+: skip best-effort tenants at admission (they stay
        queued; nothing is cancelled)."""
        return self.active_level >= 3

    def accepting_sessions(self) -> bool:
        """L4: reject new sessions with OverloadError backpressure."""
        return self.active_level < 4

    # ------------------------------------------------------ windowed reads
    # thin delegations to the shared WindowedReads machinery — kept as
    # controller methods because custom signals receive the controller
    # and call these directly (see default_signals and the bench legs)
    def window_counter(self, name: str) -> float:
        """Counter delta (summed over label series) since the previous
        poll. The first read of a name baselines it at the current
        total, so pre-existing counts never trigger the ladder."""
        return self.windows.window_counter(name)

    def gauge(self, name: str) -> float:
        """Instantaneous gauge read (summed over label series)."""
        return self.windows.gauge(name)

    def window_goodput(self) -> Tuple[float, float]:
        """(goodput ratio, token volume) over the window — NaN ratio on
        an empty window, so no-traffic polls read as healthy."""
        return self.windows.window_goodput()

    def window_quantile(self, name: str, q: float) -> float:
        """Histogram quantile over THIS window's observations: per-
        bucket count deltas vs the previous poll, interpolated exactly
        like ``Histogram.quantile``. NaN when the window saw nothing."""
        return self.windows.window_quantile(name, q)

    # -------------------------------------------------------------- polling
    def poll(self) -> int:
        """One control-loop iteration: evaluate every signal, apply
        hysteresis, maybe transition. Returns the (configured) level."""
        if not self.enabled():
            if self.level:
                self._transition(0, why="kill_switch")
            self._up_streak = self._down_streak = 0
            _DEGRADE_LEVEL.set(0.0)
            return 0
        targets = {}
        for name, fn in self.signals:
            try:
                t = int(fn(self))
            except Exception:
                t = 0              # a broken signal must not wedge service
            targets[name] = max(0, min(self.MAX_LEVEL, t))
        self.last_targets = targets
        target = max(targets.values(), default=0)
        why = max(targets, key=targets.get) if targets else "manual"
        if target > self.level:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak >= self.up_patience:
                self._transition(target, why=why)
                self._up_streak = 0
        elif target < self.level:
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak >= self.down_patience:
                # descend ONE rung per patience window: recovery is
                # deliberately slower than escalation
                self._transition(self.level - 1, why="recovery")
                self._down_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        _DEGRADE_LEVEL.set(float(self.level))
        return self.level

    def force_level(self, level: int, why: str = "manual"):
        """Operational override (and the test hook): jump straight to a
        rung, clearing the hysteresis streaks. The signal loop keeps
        running — the next poll may move the rung again."""
        level = max(0, min(self.MAX_LEVEL, int(level)))
        if level != self.level:
            self._transition(level, why=why)
        self._up_streak = self._down_streak = 0

    def _transition(self, to: int, *, why: str):
        frm, self.level = self.level, to
        self.peak_level = max(self.peak_level, to)
        direction = "up" if to > frm else "down"
        _DEGRADE_LEVEL.set(float(to))
        _DEGRADE_TRANSITIONS.inc(direction=direction, to=str(to))
        FLIGHT.record("serving.degrade", frm=frm, to=to,
                      direction=direction, why=why)
        self.transitions.append({"from": frm, "to": to,
                                 "direction": direction, "why": why,
                                 "t": self.clock()})
