"""KV handoff between engine replicas (disaggregated prefill/decode).

DistServe/Splitwise-style split: a prefill-role replica runs admission +
chunked prefill, then its finished sequences move to a decode-role
replica. The unit of transfer is a :class:`KVPayload` — the sequence's
KV blocks gathered out of the source pool into a dense ``[L, max_blocks,
block_size, H_kv, D]`` tensor pair plus the host bookkeeping needed to
resume decoding bit-exactly (cur/gen/last_tok).

:class:`KVTransfer` is the seam a real multi-host wire plugs into
(ProcessGroupNCCL send/recv in the Paddle stack, a device collective
over the mesh here). The in-process :class:`DeviceKVTransfer` is a
``jax.device_put`` onto the target pool's device — a device-to-device
copy when replicas live on different devices, a no-op view otherwise.

Both jitted programs here are fixed-shape per (engine geometry), so
repeated handoffs never recompile: gather pads the block-index vector
to ``max_blocks_per_seq`` (extra rows are gathered then ignored),
install pads with the ``num_blocks`` sentinel so the donating scatter
drops them.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.serving.types import Request


@dataclass
class KVPayload:
    """One prefilled sequence in flight between replicas."""
    req: Request
    cur: int                 # tokens stored in the source cache
    gen: int                 # tokens generated so far (1 after prefill)
    last_tok: int            # sampled but not yet written to cache
    n_blocks: int            # leading rows of k/v that are real
    block_size: int
    k: object                # [L, max_blocks, block_size, H_kv, D]
    v: object

    @property
    def tokens_bytes(self):
        return self.k.nbytes + self.v.nbytes


def _gather_blocks(k_pools, v_pools, idx):
    k = jnp.stack([p[idx] for p in k_pools])
    v = jnp.stack([p[idx] for p in v_pools])
    return k, v


_GATHER_BLOCKS_JIT = jax.jit(_gather_blocks)


def _install_blocks(cache, idx, k, v, slot, row, cur):
    k_pools = [p.at[idx].set(k[li], mode="drop")
               for li, p in enumerate(cache.k_pools)]
    v_pools = [p.at[idx].set(v[li], mode="drop")
               for li, p in enumerate(cache.v_pools)]
    tables = cache.block_tables.at[slot].set(row)
    lens = cache.lens.at[slot].set(cur)
    return type(cache)(k_pools, v_pools, tables, lens)


_INSTALL_BLOCKS_JIT = jax.jit(_install_blocks, donate_argnums=(0,))


class KVTransfer:
    """Moves a payload's tensors onto the target replica's device. The
    base class is the identity wire (same process, same device) — a
    multi-host deployment subclasses ``ship`` with its RDMA/collective
    transport; everything above this seam is transport-agnostic."""

    def ship(self, payload: KVPayload, target_engine) -> KVPayload:
        return payload


class DeviceKVTransfer(KVTransfer):
    """In-process device-to-device copy: place the gathered blocks on
    whatever device holds the target engine's pool (jax makes this a
    direct D2D copy when source and target differ, a no-op view when
    they share a device — the single-host test/bench case)."""

    def ship(self, payload: KVPayload, target_engine) -> KVPayload:
        pool = target_engine.cache.k_pools[0]
        devs = getattr(pool, "devices", None)
        dev = next(iter(devs())) if callable(devs) else None
        if dev is not None:
            payload.k = jax.device_put(payload.k, dev)
            payload.v = jax.device_put(payload.v, dev)
        return payload
