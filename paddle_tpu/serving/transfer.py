"""KV handoff between engine replicas (disaggregated prefill/decode).

DistServe/Splitwise-style split: a prefill-role replica runs admission +
chunked prefill, then its finished sequences move to a decode-role
replica. The unit of transfer is a :class:`KVPayload` — the sequence's
KV blocks gathered out of the source pool into a dense ``[L, max_blocks,
block_size, H_kv, D]`` tensor pair plus the host bookkeeping needed to
resume decoding bit-exactly (cur/gen/last_tok).

:class:`KVTransfer` is the seam a real multi-host wire plugs into
(ProcessGroupNCCL send/recv in the Paddle stack, a device collective
over the mesh here). The in-process :class:`DeviceKVTransfer` is a
``jax.device_put`` onto the target pool's device — a device-to-device
copy when replicas live on different devices, a no-op view otherwise.

Both jitted programs here are fixed-shape per (engine geometry), so
repeated handoffs never recompile: gather pads the block-index vector
to ``max_blocks_per_seq`` (extra rows are gathered then ignored),
install pads with the ``num_blocks`` sentinel so the donating scatter
drops them.

The handoff is hardened against a lossy wire (ISSUE 16): the source
engine seals each payload (:meth:`KVPayload.seal`) with its expected
geometry plus per-tensor checksums, and the router runs
:func:`validate_payload` on the shipped copy before install — a
truncated or corrupted transfer raises :class:`KVTransferError` and is
retried from the pristine source payload under a
:class:`TransportPolicy` (deadline, bounded exponential backoff,
straggler hedging to another decode replica).
"""
from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.serving.types import Request


class KVTransferError(RuntimeError):
    """A shipped payload failed geometry/checksum validation — a
    partial or corrupted transfer. The handoff is retried from the
    pristine source payload; the rejected copy is never installed."""


def _tensor_checksum(x) -> float:
    """Order-independent content checksum: the f32 sum of all elements.
    Cheap (one reduce), device-friendly, and any zeroed/truncated block
    row of real KV activations moves it far past tolerance."""
    return float(jnp.sum(jnp.asarray(x, jnp.float32)))


@dataclass
class KVPayload:
    """One prefilled sequence in flight between replicas."""
    req: Request
    cur: int                 # tokens stored in the source cache
    gen: int                 # tokens generated so far (1 after prefill)
    last_tok: int            # sampled but not yet written to cache
    n_blocks: int            # leading rows of k/v that are real
    block_size: int
    k: object                # [L, max_blocks, block_size, H_kv, D]
    v: object
    # quantized pools (ISSUE 17): int8 codes above are meaningless
    # without their per-(position, kv-head) scales — the scale rows ride
    # the same wire as [L, max_blocks, block_size, H_kv] f32 (None for
    # model-dtype pools)
    k_scale: object = None
    v_scale: object = None
    # filled by seal(): what the payload looked like when it left the
    # source pool — validate_payload checks the shipped copy against it
    expect: dict = None

    @property
    def tokens_bytes(self):
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n

    def seal(self):
        """Record the wire contract at the source: geometry + content
        checksums (scales included for quantized payloads — a corrupted
        scale row silently rescales whole positions). Called once by
        ``extract_sequence`` before the payload leaves the engine."""
        self.expect = {
            "shape": tuple(self.k.shape),
            "cur": self.cur,
            "n_blocks": self.n_blocks,
            "ksum": _tensor_checksum(self.k),
            "vsum": _tensor_checksum(self.v),
            "quant": self.k_scale is not None,
        }
        if self.k_scale is not None:
            self.expect["kssum"] = _tensor_checksum(self.k_scale)
            self.expect["vssum"] = _tensor_checksum(self.v_scale)
        return self


def validate_payload(payload: KVPayload, target_engine) -> KVPayload:
    """Reject partial/corrupt transfers before they touch the target
    pool. Geometry is checked against both the seal and the target
    engine; checksums against the seal (tolerance covers f32 summation
    order, not content). Unsealed payloads (hand-built in tests, or a
    custom transport that re-packs) get the geometry checks only."""
    k, v = payload.k, payload.v
    pool = target_engine.cache.k_pools[0]
    if tuple(k.shape) != tuple(v.shape):
        raise KVTransferError(
            f"k/v geometry diverged in flight: {tuple(k.shape)} vs "
            f"{tuple(v.shape)}")
    if k.shape[0] != len(target_engine.cache.k_pools) \
            or tuple(k.shape[2:]) != tuple(pool.shape[1:]):
        raise KVTransferError(
            f"payload geometry {tuple(k.shape)} does not match the "
            f"target pool [{len(target_engine.cache.k_pools)}, *, "
            f"{tuple(pool.shape[1:])}]")
    if payload.n_blocks * payload.block_size < payload.cur:
        raise KVTransferError(
            f"payload truncated: {payload.n_blocks} blocks × "
            f"{payload.block_size} cannot cover cur={payload.cur}")
    # quantized-pool compatibility: int8 codes must land in an int8
    # pool WITH their scales; a bf16 payload must not target one
    quant_target = bool(getattr(target_engine.cache, "k_scales", ()))
    quant_payload = payload.k_scale is not None
    if quant_target != quant_payload:
        raise KVTransferError(
            f"KV dtype mismatch: payload is "
            f"{'int8+scales' if quant_payload else 'model-dtype'} but the "
            f"target pool is "
            f"{'int8+scales' if quant_target else 'model-dtype'} — "
            "replicas in one handoff group must share kv_dtype")
    if jnp.asarray(k).dtype != pool.dtype:
        raise KVTransferError(
            f"payload element dtype {jnp.asarray(k).dtype} != target "
            f"pool dtype {pool.dtype}")
    if quant_payload and (tuple(payload.k_scale.shape) != tuple(k.shape[:4])
                          or tuple(payload.v_scale.shape)
                          != tuple(v.shape[:4])):
        raise KVTransferError(
            f"scale geometry {tuple(payload.k_scale.shape)} does not "
            f"match the code blocks {tuple(k.shape[:4])}")
    exp = payload.expect
    if exp is not None:
        if (tuple(k.shape) != exp["shape"] or payload.cur != exp["cur"]
                or payload.n_blocks != exp["n_blocks"]):
            raise KVTransferError(
                f"payload drifted from its seal: shape={tuple(k.shape)} "
                f"cur={payload.cur} n_blocks={payload.n_blocks}, sealed "
                f"{exp['shape']}/{exp['cur']}/{exp['n_blocks']}")
        if exp.get("quant", False) != quant_payload:
            raise KVTransferError(
                "payload quantization drifted from its seal (scales "
                "added or dropped in flight)")
        checks = [(k, exp["ksum"], "k"), (v, exp["vsum"], "v")]
        if quant_payload:
            checks += [(payload.k_scale, exp["kssum"], "k-scale"),
                       (payload.v_scale, exp["vssum"], "v-scale")]
        for x, want, name in checks:
            got = _tensor_checksum(x)
            if abs(got - want) > 1e-3 * max(1.0, abs(want)):
                raise KVTransferError(
                    f"{name}-checksum mismatch (partial/corrupt "
                    f"transfer): got {got!r}, sealed {want!r}")
    return payload


class TransportPolicy:
    """Retry/deadline/hedging policy for one handoff delivery.

    ``deadline_s=None`` derives the straggler deadline from live data:
    ``deadline_margin ×`` the p95 of ``router_kv_transfer_seconds``
    (floored at ``min_deadline_s``), once at least ``min_samples``
    deliveries have been observed — before that there is no deadline
    and no hedging, so cold starts never hedge on noise. Retries use
    bounded exponential backoff through the injectable ``sleep``."""

    def __init__(self, *, deadline_s: float = None,
                 deadline_margin: float = 3.0,
                 min_deadline_s: float = 0.05, min_samples: int = 8,
                 max_attempts: int = 3, backoff_base_s: float = 0.005,
                 backoff_max_s: float = 0.1, hedge: bool = True,
                 sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.deadline_s = deadline_s
        self.deadline_margin = deadline_margin
        self.min_deadline_s = min_deadline_s
        self.min_samples = min_samples
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.hedge = hedge
        self.sleep = sleep

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt+1`` (attempt is 0-based)."""
        return min(self.backoff_base_s * (2 ** attempt), self.backoff_max_s)

    def deadline(self, hist) -> float:
        """The straggler deadline, or None while underinformed."""
        if self.deadline_s is not None:
            return self.deadline_s
        count = sum(s.count for s in hist._series.values())
        if count < self.min_samples:
            return None
        p95 = hist.quantile(0.95)
        if p95 != p95:                       # NaN: no data
            return None
        return max(self.min_deadline_s, self.deadline_margin * p95)


def _gather_blocks(k_pools, v_pools, idx):
    # also reused over the SCALE pools of a quantized cache — the
    # trailing dims differ, so each use compiles its own entry
    k = jnp.stack([p[idx] for p in k_pools])
    v = jnp.stack([p[idx] for p in v_pools])
    return k, v


_GATHER_BLOCKS_JIT = jax.jit(_gather_blocks)


def _install_blocks(cache, idx, k, v, ks, vs, slot, row, cur):
    """``ks``/``vs`` are the per-(position, kv-head) scale blocks of a
    quantized payload, or None — the None arms are distinct pytree
    structures, so one jit serves both pool flavours."""
    k_pools = [p.at[idx].set(k[li], mode="drop")
               for li, p in enumerate(cache.k_pools)]
    v_pools = [p.at[idx].set(v[li], mode="drop")
               for li, p in enumerate(cache.v_pools)]
    k_scales, v_scales = cache.k_scales, cache.v_scales
    if ks is not None:
        k_scales = tuple(p.at[idx].set(ks[li], mode="drop")
                         for li, p in enumerate(cache.k_scales))
        v_scales = tuple(p.at[idx].set(vs[li], mode="drop")
                         for li, p in enumerate(cache.v_scales))
    tables = cache.block_tables.at[slot].set(row)
    lens = cache.lens.at[slot].set(cur)
    return type(cache)(k_pools, v_pools, tables, lens, k_scales, v_scales)


_INSTALL_BLOCKS_JIT = jax.jit(_install_blocks, donate_argnums=(0,))

# env-flip hygiene (ISSUE 17): these jits trace over the cache pytree,
# whose quantize-on-write path reads PT_QUANT_KV at trace time —
# clear_jit_caches() must reach them too
from paddle_tpu.models.paged import _EXTRA_CLEAR as _PAGED_EXTRA_CLEAR  # noqa: E402

_PAGED_EXTRA_CLEAR.extend([_GATHER_BLOCKS_JIT, _INSTALL_BLOCKS_JIT])


class KVTransfer:
    """Moves a payload's tensors onto the target replica's device. The
    base class is the identity wire (same process, same device) — a
    multi-host deployment subclasses ``ship`` with its RDMA/collective
    transport; everything above this seam is transport-agnostic."""

    def ship(self, payload: KVPayload, target_engine) -> KVPayload:
        return payload


class DeviceKVTransfer(KVTransfer):
    """In-process device-to-device copy: place the gathered blocks on
    whatever device holds the target engine's pool (jax makes this a
    direct D2D copy when source and target differ, a no-op view when
    they share a device — the single-host test/bench case)."""

    def ship(self, payload: KVPayload, target_engine) -> KVPayload:
        pool = target_engine.cache.k_pools[0]
        devs = getattr(pool, "devices", None)
        dev = next(iter(devs())) if callable(devs) else None
        if dev is not None:
            payload.k = jax.device_put(payload.k, dev)
            payload.v = jax.device_put(payload.v, dev)
            if payload.k_scale is not None:
                payload.k_scale = jax.device_put(payload.k_scale, dev)
                payload.v_scale = jax.device_put(payload.v_scale, dev)
        return payload
