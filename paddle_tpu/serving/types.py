"""Request/response types and intake errors shared by the serving layers.

Split out of the monolithic ``serving.py`` (ISSUE 7) so the scheduler,
KV-manager, executor, engine, and router can all import them without
cycles. Everything here is host-side dataclass state — nothing traces
into a jitted program.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


class QueueFullError(RuntimeError):
    """Admission queue at ``max_queue_len`` — backpressure: the caller
    should shed load or retry later, NOT buffer unboundedly here."""


class EngineDrainingError(RuntimeError):
    """``drain()`` was called — the engine finishes in-flight work but
    admits nothing new."""


class OverloadError(QueueFullError):
    """The degradation ladder is at L4: new sessions are rejected with
    explicit backpressure until the cluster recovers. A subclass of
    :class:`QueueFullError` so existing shed/retry handlers compose —
    the correct client reaction (back off, retry later) is the same."""


@dataclass
class Request:
    """One generation request. ``stream`` (optional) is called as
    ``stream(request, token)`` the tick each new token is sampled.
    ``num_beams > 1``: beam search — the request occupies num_beams cache
    slots, selection mirrors ``decoding.beam_search`` exactly, and the
    BEST hypothesis lands in ``tokens`` when the request finishes (no
    streaming; tail past a hypothesis' first EOS is EOS-filled)."""
    prompt: object                       # 1-D int tokens
    max_new_tokens: int = 32
    req_id: int = None
    stream: object = None
    num_beams: int = 1
    length_penalty: float = 1.0
    # per-request sampling overrides (None = the engine's defaults):
    temperature: float = None
    top_p: float = None
    # robustness knobs (None = unbounded):
    #   deadline_s    total wall-clock budget from submission — expired
    #                 requests finish with finish_reason="timeout"
    #                 (whatever tokens were generated stay available)
    #   max_queue_s   max time WAITING for admission; a request that
    #                 can't enter a slot in time also times out
    deadline_s: float = None
    max_queue_s: float = None
    # router affinity (ISSUE 7): requests sharing a session_id stick to
    # one replica, so a session's prefix-cache blocks stay local
    session_id: object = None
    # multi-tenancy (ISSUE 14):
    #   adapter_id   LoRA adapter this request decodes under (must be
    #                registered with the engine's AdapterStore); None =
    #                the base model. Also part of the prefix-cache key —
    #                KV blocks never cross adapter identities.
    #   tenant_id    fair-scheduling identity: queued tenants share
    #                admission capacity by token-budget-weighted deficit
    #                (None = legacy FCFS ordering among the unlabelled)
    #   grammar      constrained decoding: a TokenMaskAutomaton (or a
    #                (regex, vocab) construction handled by the caller) —
    #                every sampled/accepted token satisfies its mask
    adapter_id: object = None
    tenant_id: object = None
    grammar: object = None
    # filled by the engine:
    tokens: list = field(default_factory=list)   # generated tokens
    done: bool = False
    finish_reason: str = None
    _submit_t: float = None              # engine clock at add_request
    _first_tok_t: float = None           # engine clock at first token (TTFT)
    _last_tok_t: float = None            # engine clock at newest token
    beam_score: float = None
    # set on preemption: prompt + tokens generated so far — the resume
    # prefill recomputes the whole sequence (prefix-cache hits make the
    # recompute cheap when its old blocks are still parked)
    _resume: object = None
    # scheduler-side prefix-match memo: (cache_epoch, prompt_len, match).
    # A queued request is re-probed only when the manager's epoch moved
    # (eviction/commit) or its effective prompt changed (resume)
    _match_memo: tuple = None
    # token span adopted from the radix prefix cache at admission (ISSUE
    # 11): the spec-decode draft seed uses it to skip re-embedding the
    # adopted prefix when the draft cache still holds those tokens
    _adopted: int = 0
    # request tracker (ISSUE 9): trace_id is minted at first submit while
    # tracking is enabled (None = untracked, every tracker call no-ops);
    # trace_summary is the finished timeline summary, same dict /requests
    # serves
    trace_id: object = None
    trace_summary: object = None
    # set by the Router once ITS admission gate (queue depth + ladder
    # L4) has passed — replica engines then skip their own session gate,
    # so accepted work is never re-rejected mid-dispatch or on requeue
    _preadmitted: bool = False

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)


@dataclass
class _BeamGroup:
    """Engine-side state of one in-flight beam request (K cache slots +
    the device-resident selection state shared with paged_beam_search)."""
    req: Request
    slots: list
    s: int                                # prompt length
    i: int = 0                            # selects done
    sid: dict = field(default_factory=dict)   # beam j -> BlockManager key
    running_lp: object = None
    seqs: object = None
    fin_seqs: object = None
    fin_scores: object = None
    logp: object = None                   # [K, vocab] device, pre-select
