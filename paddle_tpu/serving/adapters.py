"""AdapterStore: the multi-tenant LoRA registry + device cache (ISSUE 14).

A production fleet serves many fine-tuned variants of ONE base model.
The S-LoRA/Punica pattern this store feeds: every tenant's adapter is a
rank-r pair (A, B) per targeted projection; the batch runs the shared
base forward once, and a grouped rank-r correction
``y += scale * (x @ A) @ B`` is added per slot according to that slot's
adapter. For that to be one fused program, the resident adapters live
as STACKED device tensors — ``[L, capacity, in, r_max]`` per projection
— indexed by a per-slot cache index, so heterogeneous batches flow
through the grouped-GEMM kernel as ragged per-adapter segments with no
per-adapter dispatch.

This module owns the lifecycle around that:

* ``register(adapter_id, state_dict)`` — host-resident adapter sets,
  validated STRICTLY via :func:`~paddle_tpu.peft.lora_load_state_dict`
  (missing/unexpected keys raise ``ValueError``), rank-padded to
  ``max_rank`` with the ``alpha/r`` scale folded into B (zero-padding
  keeps the folded product exact).
* ``acquire(adapter_id)`` — LRU device cache of ``capacity`` stacked
  slots with host→device hot-swap; returns the cache index and takes a
  REF-COUNT pin so an adapter in use by a scheduled slot is never
  evicted. ``release`` drops the pin. When every resident entry is
  pinned and a new adapter needs a slot, ``acquire`` raises — the
  scheduler defers that admission rather than corrupt a live batch.
* the ``serving.adapter_swap`` chaos site fires BEFORE the upload
  mutates anything, so an injected fault leaves the cache, the pins,
  and the free list exactly as they were (exception-atomic; the
  scheduler turns it into a deferred admission).
"""
from __future__ import annotations

import re
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from paddle_tpu.peft import lora_load_state_dict, lora_targets
from paddle_tpu.serving.telemetry import (_ADAPTER_EVICTIONS, _ADAPTER_HITS,
                                          _ADAPTER_MISSES, _ADAPTER_RESIDENT,
                                          _ADAPTER_UPLOADS)
from paddle_tpu.utils.faults import fault_point

# serving targets the attention projections (the fused qkv and the
# output proj) — the pair the paged forwards thread the correction into
SERVING_TARGETS = ("qkv_proj", "o_proj")
_KIND_OF = {"qkv_proj": "qkv", "o_proj": "o"}


class AdapterStore:
    """Registered LoRA adapter sets + a device-resident stacked cache."""

    def __init__(self, model, *, capacity: int = 4, max_rank: int = 8,
                 target_modules=SERVING_TARGETS):
        import jax
        from paddle_tpu.core.module import _path_to_str
        self.capacity = int(capacity)
        self.max_rank = int(max_rank)
        if self.capacity < 1:
            raise ValueError("adapter cache capacity must be >= 1")
        paths = lora_targets(model, target_modules)
        flat, _ = jax.tree_util.tree_flatten_with_path(model)
        shapes = {_path_to_str(p): tuple(leaf.shape) for p, leaf in flat
                  if hasattr(leaf, "shape")}
        # path -> (layer, kind); layers must tile 0..L-1 for each kind
        self._slot_of: dict[str, tuple[int, str]] = {}
        dims: dict[str, tuple[int, int]] = {}
        layers = set()
        for p in paths:
            m = re.search(r"layers\.(\d+)\.", p)
            leaf = p.split(".")[-2] if p.endswith(".weight") else \
                p.split(".")[-1]
            if m is None or leaf not in _KIND_OF:
                raise ValueError(f"cannot place LoRA target {p!r}")
            li, kind = int(m.group(1)), _KIND_OF[leaf]
            self._slot_of[p] = (li, kind)
            layers.add(li)
            d = shapes[p]
            if dims.setdefault(kind, d) != d:
                raise ValueError(f"inconsistent {kind} shapes across layers")
        self.num_layers = max(layers) + 1
        self._paths = paths
        self._dims = dims                       # kind -> (fan_in, fan_out)
        self._host: dict[object, dict[str, tuple[np.ndarray, np.ndarray]]] \
            = {}
        self._resident: OrderedDict[object, int] = OrderedDict()  # MRU last
        self._pins: dict[object, int] = {}
        self._free = list(range(self.capacity - 1, -1, -1))
        self._stacks = {}
        for kind, (k, n) in dims.items():
            self._stacks[kind + "_a"] = jnp.zeros(
                (self.num_layers, self.capacity, k, self.max_rank),
                jnp.float32)
            self._stacks[kind + "_b"] = jnp.zeros(
                (self.num_layers, self.capacity, self.max_rank, n),
                jnp.float32)

    # ----------------------------------------------------------- registry
    def register(self, adapter_id, state_dict: dict):
        """Validate and install a tenant's adapter set (host-resident).
        Re-registering an UNPINNED id replaces it (and drops any stale
        device residency); a pinned id is in use and refuses."""
        if adapter_id is None:
            raise ValueError("adapter_id None is reserved for the base model")
        if self._pins.get(adapter_id):
            raise ValueError(f"adapter {adapter_id!r} is pinned by "
                             "scheduled requests; cannot re-register")
        template = {p: {"a": np.zeros((shape[0], 1), np.float32),
                        "b": np.zeros((1, shape[1]), np.float32)}
                    for p, shape in ((p, self._dims[self._slot_of[p][1]])
                                     for p in self._paths)}
        template["_scale"] = np.zeros((), np.float32)
        tree = lora_load_state_dict(template, state_dict)   # strict keys
        scale = float(tree["_scale"])
        per_kind: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for kind, (k, n) in self._dims.items():
            per_kind[kind] = (
                np.zeros((self.num_layers, k, self.max_rank), np.float32),
                np.zeros((self.num_layers, self.max_rank, n), np.float32))
        for p in self._paths:
            li, kind = self._slot_of[p]
            k, n = self._dims[kind]
            a = np.asarray(tree[p]["a"], np.float32)
            b = np.asarray(tree[p]["b"], np.float32)
            r = a.shape[1] if a.ndim == 2 else -1
            if a.shape != (k, r) or b.shape != (r, n) or r < 1:
                raise ValueError(
                    f"adapter {adapter_id!r}: {p} has A{a.shape}/B{b.shape}"
                    f", expected A({k}, r)/B(r, {n})")
            if r > self.max_rank:
                raise ValueError(
                    f"adapter {adapter_id!r}: rank {r} exceeds the store's "
                    f"max_rank {self.max_rank}")
            # zero-padding to max_rank keeps scale*(x@A)@B exact
            per_kind[kind][0][li, :, :r] = a
            per_kind[kind][1][li, :r, :] = b * scale   # fold the scale in
        self._host[adapter_id] = per_kind
        idx = self._resident.pop(adapter_id, None)
        if idx is not None:                    # stale device copy: drop it
            self._free.append(idx)
            _ADAPTER_RESIDENT.set(len(self._resident))

    def known(self, adapter_id) -> bool:
        return adapter_id in self._host

    # ------------------------------------------------------- device cache
    def ensure(self, adapter_id) -> int:
        """Make ``adapter_id`` device-resident; returns its cache index.
        Exception-atomic: the ``serving.adapter_swap`` site fires before
        any mutation, and a failed victim search mutates nothing."""
        idx = self._resident.get(adapter_id)
        if idx is not None:
            self._resident.move_to_end(adapter_id)
            _ADAPTER_HITS.inc()
            return idx
        host = self._host.get(adapter_id)
        if host is None:
            raise KeyError(f"adapter {adapter_id!r} is not registered")
        _ADAPTER_MISSES.inc()
        victim = None
        if not self._free:
            for aid in self._resident:         # LRU first
                if not self._pins.get(aid):
                    victim = aid
                    break
            if victim is None:
                raise RuntimeError(
                    "adapter cache exhausted: all "
                    f"{self.capacity} resident adapters are pinned")
        fault_point("serving.adapter_swap", store=self, adapter=adapter_id,
                    victim=victim)
        if victim is None:
            idx = self._free.pop()
        else:
            idx = self._resident.pop(victim)
            _ADAPTER_EVICTIONS.inc()
        for kind, (a, b) in host.items():
            self._stacks[kind + "_a"] = \
                self._stacks[kind + "_a"].at[:, idx].set(a)
            self._stacks[kind + "_b"] = \
                self._stacks[kind + "_b"].at[:, idx].set(b)
        self._resident[adapter_id] = idx
        _ADAPTER_UPLOADS.inc()
        _ADAPTER_RESIDENT.set(len(self._resident))
        return idx

    def acquire(self, adapter_id) -> int:
        """``ensure`` + pin: the index stays valid until ``release``."""
        idx = self.ensure(adapter_id)
        self._pins[adapter_id] = self._pins.get(adapter_id, 0) + 1
        return idx

    def release(self, adapter_id):
        n = self._pins.get(adapter_id, 0) - 1
        if n > 0:
            self._pins[adapter_id] = n
        else:
            self._pins.pop(adapter_id, None)

    def index_of(self, adapter_id) -> int:
        """Cache index of a RESIDENT adapter (stable while pinned)."""
        return self._resident[adapter_id]

    def stacks(self) -> dict:
        """The stacked device tensors the forwards index:
        ``{qkv_a, qkv_b, o_a, o_b}``, each ``[L, capacity, ...]``."""
        return dict(self._stacks)

    def assert_quiescent(self):
        """No pins outstanding (every scheduled slot released its hold)."""
        assert not self._pins, f"adapter pin leak: {self._pins}"
