"""ModelExecutor: the device half of the decomposed engine (ISSUE 7).

Owns the paged KV cache, the draft-model dense cache, the engine PRNG
key, and every jitted program the tick runs — slot-aware prefill, the
chunked-prefill/verify forwards, the fused decode tick, beam-group
cache updates, and row sampling. Callers hand in fixed-shape numpy
staging arrays and get logits/tokens back; all cache donation happens
inside this class, so an exception raised BEFORE a call here leaves
``self.cache`` intact (the exception-atomicity contract the chaos
sites rely on).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.decoding import KVCache, _sample_rows
from paddle_tpu.models.paged import (PagedKVCache, _ASYNC_TICK_JIT,
                                     _BEAM_GROUP_UPDATE_JIT,
                                     _PREFILL_CHUNK_JIT, _PREFILL_JIT,
                                     _PREFIX_COW_JIT, _REWIND_LENS_JIT,
                                     _TICK_JIT, _VERIFY_CHUNK_JIT,
                                     _prefix_cow_update,
                                     llama_decode_tick,
                                     llama_prefill_chunk_paged,
                                     llama_prefill_paged,
                                     llama_verify_chunk_paged,
                                     spec_rewind_lens)
from paddle_tpu.models.speculative import _FWD_ROWS_JIT

# module-level so its compile cache persists across admissions
_SAMPLE_ROWS_JIT = jax.jit(_sample_rows, static_argnums=(4,))


class ModelExecutor:
    """Jitted prefill/decode/verify programs over one paged KV pool.

    ``cp > 1`` (context parallelism, ISSUE 18) shards the pool's physical
    blocks over a ``cp`` mesh axis — member s owns GLOBAL block ids
    [s*per, (s+1)*per), per = num_blocks/cp — while weights, block
    tables, lens and every activation stay replicated. All jitted
    programs then run inside ``shard_map``: scatters drop non-owned
    writes, attention emits per-shard online-softmax partials, and the
    merges (psum for decode, ring/Ulysses for chunk prefill) are
    bit-identical on every member, so sampling stays replicated and the
    host engine sees the exact single-device contract."""

    def __init__(self, model, *, num_slots, num_blocks, block_size,
                 max_blocks_per_seq, top_k=None, seed=0, draft_model=None,
                 spec_k=4, max_seq_len=None, kv_dtype=None, cp=1):
        cfg = model.cfg
        self.model = model
        self.top_k = top_k
        self.rng = jax.random.PRNGKey(seed)
        self.cp = int(cp)
        self.mesh = None
        # kv_dtype="int8": int8 block pools + parallel per-(position,
        # kv-head) f32 scale pools; every jit here quantizes on write and
        # dequantizes on read (ISSUE 17). None = pools in the model dtype.
        self.cache = PagedKVCache.init(
            cfg.num_hidden_layers, num_blocks, block_size,
            cfg.num_key_value_heads,
            cfg.hidden_size // cfg.num_attention_heads,
            num_slots, max_blocks_per_seq, cfg.dtype, kv_dtype=kv_dtype)
        if self.cp > 1:
            self._init_cp(num_blocks)
        self.draft_model = draft_model
        self._draft_cache = None
        if draft_model is not None:
            dcfg = draft_model.cfg
            self._draft_cache = KVCache.init(
                dcfg.num_hidden_layers, num_slots,
                max_seq_len + spec_k + 2,
                dcfg.num_key_value_heads,
                dcfg.hidden_size // dcfg.num_attention_heads, dcfg.dtype)

    # ------------------------------------------------- context parallelism
    def _init_cp(self, num_blocks):
        """Build the cp mesh, lay the pools out sharded on their block
        axis, and compile per-executor shard_map'd twins of every cache
        program. Per-executor (not module-level) jits: their traces bake
        the mesh + PT_CP_IMPL, and they die with the executor, so the
        ``clear_jit_caches`` env-flip contract is construction-scoped for
        free."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed._compat import shard_map
        from paddle_tpu.distributed.mesh import HybridMesh

        cp = self.cp
        devs = jax.devices()
        if cp > len(devs):
            raise ValueError(f"cp={cp} exceeds {len(devs)} devices")
        if num_blocks % cp:
            raise ValueError(
                f"num_blocks={num_blocks} must divide by cp={cp} "
                "(equal per-shard pools)")
        self.mesh = HybridMesh(cp=cp, devices=devs[:cp])
        pool_s = NamedSharding(self.mesh.mesh, P("cp"))
        rep_s = NamedSharding(self.mesh.mesh, P())
        c = self.cache
        self.cache = PagedKVCache(
            [jax.device_put(p, pool_s) for p in c.k_pools],
            [jax.device_put(p, pool_s) for p in c.v_pools],
            jax.device_put(c.block_tables, rep_s),
            jax.device_put(c.lens, rep_s),
            tuple(jax.device_put(p, pool_s) for p in c.k_scales),
            tuple(jax.device_put(p, pool_s) for p in c.v_scales))
        # pytree-PREFIX spec: each field leaf broadcasts over its subtree
        cs = PagedKVCache(P("cp"), P("cp"), P(), P(), P("cp"), P("cp"))
        R = P()

        def smap(fn, in_specs, out_specs):
            return shard_map(fn, mesh=self.mesh.mesh,
                             in_specs=in_specs, out_specs=out_specs)

        self._cp_prefill = jax.jit(smap(
            functools.partial(llama_prefill_paged, cp_axis="cp"),
            (R, R, R, cs, R, R), (R, cs)))
        self._cp_prefill_chunk = jax.jit(smap(
            functools.partial(llama_prefill_chunk_paged, cp_axis="cp"),
            (R, R, R, R, cs, R, R), (R, cs)), donate_argnums=(4,))
        self._cp_verify_chunk = jax.jit(smap(
            functools.partial(llama_verify_chunk_paged, cp_axis="cp"),
            (R, R, R, R, cs, R, R), (R, cs)), donate_argnums=(4,))
        self._cp_rewind = jax.jit(smap(
            spec_rewind_lens, (cs, R, R), cs), donate_argnums=(0,))
        top_k = self.top_k

        # top_k / want_logp are STATIC in the tick; bake them (beams — the
        # only want_logp consumer — are refused under cp by the engine) so
        # shard_map sees purely positional array args
        def _tick(model, tokens, cache, active, rows, cols, vals, rng,
                  temps, top_ps, bias):
            return llama_decode_tick(
                model, tokens, cache, active, rows, cols, vals, rng,
                temps, top_ps, top_k, False, None, bias, cp_axis="cp")

        self._cp_tick = jax.jit(smap(
            _tick, (R, R, cs, R, R, R, R, R, R, R, R), (R, R, cs)),
            donate_argnums=(2,))
        self._cp_cow = jax.jit(smap(
            functools.partial(_prefix_cow_update, cp_axis="cp"),
            (cs, R, R), cs), donate_argnums=(0,))

    def next_key(self):
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def _no_cp_lora(self, lora):
        if lora is not None and self.cp > 1:
            raise NotImplementedError(
                "multi-LoRA under context parallelism (cp > 1) is not "
                "supported yet — serve adapters with cp=1")
        return lora

    # ------------------------------------------------------------ prefill
    def prefill(self, ids, lens, slots, rows, lora=None):
        """Slot-aware padded prefill: admitted prompts scattered into
        their cache slots while other slots keep decoding state.
        ``lora`` (optional pytree, see ``models.paged._lora_delta``)
        applies the batched multi-LoRA correction per row."""
        if self.cp > 1:
            self._no_cp_lora(lora)
            logits, self.cache = self._cp_prefill(
                self.model, jnp.asarray(ids), jnp.asarray(lens),
                self.cache, jnp.asarray(slots), jnp.asarray(rows))
            return logits
        logits, self.cache = _PREFILL_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(lens),
            self.cache, jnp.asarray(slots), jnp.asarray(rows), lora=lora)
        return logits

    def prefill_chunk(self, ids, lens, offs, slots, rows, lora=None):
        """One chunk per row, written from an arbitrary offset over the
        slot's pool prefix (chunked prefill / prefix-cache resume)."""
        if self.cp > 1:
            self._no_cp_lora(lora)
            logits, self.cache = self._cp_prefill_chunk(
                self.model, jnp.asarray(ids), jnp.asarray(lens),
                jnp.asarray(offs), self.cache, jnp.asarray(slots),
                jnp.asarray(rows))
            return logits
        logits, self.cache = _PREFILL_CHUNK_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(lens),
            jnp.asarray(offs), self.cache, jnp.asarray(slots),
            jnp.asarray(rows), lora=lora)
        return logits

    def verify_chunk(self, ids, clens, offs, slot_ids, rows, lora=None):
        """Target forward over each slot's proposal window (spec decode);
        shares the chunked-prefill program shape."""
        if self.cp > 1:
            self._no_cp_lora(lora)
            logits, self.cache = self._cp_verify_chunk(
                self.model, jnp.asarray(ids), jnp.asarray(clens),
                jnp.asarray(offs), self.cache, jnp.asarray(slot_ids),
                jnp.asarray(rows))
            return logits
        logits, self.cache = _VERIFY_CHUNK_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(clens),
            jnp.asarray(offs), self.cache, jnp.asarray(slot_ids),
            jnp.asarray(rows), lora=lora)
        return logits

    def rewind_lens(self, slots, lens):
        """Length-pointer-only rewind after a partial spec accept."""
        if self.cp > 1:
            self.cache = self._cp_rewind(self.cache, jnp.asarray(slots),
                                         jnp.asarray(lens))
            return
        self.cache = _REWIND_LENS_JIT(self.cache, jnp.asarray(slots),
                                      jnp.asarray(lens))

    # ------------------------------------------------------------- decode
    def decode_tick(self, last_tok, run_mask, rows, cols, vals, temps,
                    top_ps, need_logp, lora=None, bias=None):
        """The fused one-token tick: incremental table update + paged
        attention + on-device sampling. Returns (sampled [num_slots],
        logp [num_slots, vocab] or None per ``need_logp``). ``lora`` is
        the per-slot multi-LoRA pytree; ``bias`` a [num_slots, V]
        grammar-mask logit bias applied before sampling."""
        sub = self.next_key()
        if self.cp > 1:
            self._no_cp_lora(lora)
            if need_logp:
                raise NotImplementedError(
                    "beam search (want_logp) under cp > 1 is not supported")
            nxt, logp, self.cache = self._cp_tick(
                self.model, jnp.asarray(last_tok), self.cache,
                jnp.asarray(run_mask), jnp.asarray(rows),
                jnp.asarray(cols), jnp.asarray(vals), sub,
                jnp.asarray(temps), jnp.asarray(top_ps),
                None if bias is None else jnp.asarray(bias))
            return nxt, logp
        nxt, logp, self.cache = _TICK_JIT(
            self.model, jnp.asarray(last_tok), self.cache,
            jnp.asarray(run_mask), jnp.asarray(rows), jnp.asarray(cols),
            jnp.asarray(vals), sub, jnp.asarray(temps),
            jnp.asarray(top_ps), self.top_k, need_logp, lora=lora,
            logit_bias=(None if bias is None else jnp.asarray(bias)))
        return nxt, logp

    def decode_tick_async(self, tokens, active, stop, gen, max_gen,
                          temps, top_ps, eos_id):
        """Depth-K pipelined tick (ISSUE 20): ``tokens``/``stop``/``gen``
        are DEVICE arrays threaded from the previous call — the sampled
        token array feeds the next call without a host round trip, and
        EOS/max-gen stop is evaluated in the jit via the stop mask. No
        table updates, grammar bias, LoRA, or beam logp: the engine
        drains its window and takes :meth:`decode_tick` for any tick
        needing them. Returns (nxt, ran, stop', gen'), all on device."""
        sub = self.next_key()
        nxt, ran, stop, gen, self.cache = _ASYNC_TICK_JIT(
            self.model, tokens, self.cache, active, stop, gen, max_gen,
            sub, jnp.asarray(temps), jnp.asarray(top_ps),
            jnp.int32(eos_id), self.top_k)
        return nxt, ran, stop, gen

    def apply_block_copies(self, pairs):
        """Radix prefix cache COW plan: copy each (src, dst) pool block
        before this tick's programs write the pool. Padded to a fixed
        width so the jit compiles once; longer plans run in batches."""
        nb = self.cache.num_blocks
        width = 8
        cow = self._cp_cow if self.cp > 1 else _PREFIX_COW_JIT
        for i in range(0, len(pairs), width):
            chunk = pairs[i:i + width]
            src = np.full(width, nb, np.int32)      # sentinel = no copy
            dst = np.full(width, nb, np.int32)
            for j, (s, d) in enumerate(chunk):
                src[j], dst[j] = s, d
            self.cache = cow(self.cache, jnp.asarray(src),
                             jnp.asarray(dst))

    def beam_group_update(self, slots, rows, lens_val, copy_src, copy_dst):
        """Install forked beam tables + partial-block copy-on-write."""
        if self.cp > 1:
            raise NotImplementedError(
                "beam search under context parallelism (cp > 1) is not "
                "supported yet")
        self.cache = _BEAM_GROUP_UPDATE_JIT(
            self.cache, jnp.asarray(slots, jnp.int32), jnp.asarray(rows),
            jnp.asarray(lens_val, jnp.int32), jnp.asarray(copy_src),
            jnp.asarray(copy_dst))

    # ------------------------------------------------------------- sample
    def sample(self, logits, temps, top_ps, key=None, bias=None):
        """Per-row temperature/top-k/top-p sampling (host fetch).
        ``bias`` ([rows, V], 0 / -1e30) is the grammar-mask addend."""
        sub = self.next_key() if key is None else key
        return np.asarray(_SAMPLE_ROWS_JIT(
            logits.astype(jnp.float32), sub, jnp.asarray(temps),
            jnp.asarray(top_ps), self.top_k,
            bias=(None if bias is None else jnp.asarray(bias))))

    # -------------------------------------------------------------- draft
    def draft_rows(self, ids, rp, cl):
        """One draft-model forward over per-row chunks of the dense
        draft cache (speculative proposal feeds)."""
        logits, self._draft_cache = _FWD_ROWS_JIT(
            self.draft_model, jnp.asarray(ids), self._draft_cache,
            jnp.asarray(rp, jnp.int32), None, jnp.asarray(cl, jnp.int32))
        return logits
