"""Context-parallel serving instruments (ISSUE 18).

Separate from ``telemetry.py`` so a cp=1 engine never registers (or
exports) the cp family, and so the executor can observe the gather
histogram without importing the whole engine telemetry surface.

``serving_cp_shard_blocks`` is derived host-side: the pool is split
contiguously — shard ``s`` owns global block ids ``[s·per, (s+1)·per)``
with ``per = num_blocks // cp`` — so a BlockManager's allocated-id set
buckets into per-shard occupancy without touching the device.
"""
from __future__ import annotations

from paddle_tpu.observability import METRICS

_CP_AXIS = METRICS.gauge(
    "serving_cp_axis_size",
    "context-parallel axis size of the serving engine (1 = cp disabled)")
_CP_SHARD_BLOCKS = METRICS.gauge(
    "serving_cp_shard_blocks",
    "allocated KV blocks resident on each cp shard (contiguous split: "
    "shard s owns global ids [s*per, (s+1)*per))", labelnames=("shard",))
_CP_GATHER_S = METRICS.histogram(
    "serving_cp_gather_seconds",
    "device wall time of one cp>1 decode tick — the fused forward whose "
    "cp-added cost over the cp=1 baseline is the cross-shard partial "
    "gather/merge (psum of the per-layer online-softmax triple)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))


def shard_occupancy(allocated_ids, num_blocks: int, cp: int) -> list[int]:
    """Bucket allocated GLOBAL block ids into per-shard counts under the
    contiguous split. ``allocated_ids`` is any iterable of ints."""
    per = num_blocks // cp
    counts = [0] * cp
    for b in allocated_ids:
        s = int(b) // per
        if 0 <= s < cp:
            counts[s] += 1
    return counts
