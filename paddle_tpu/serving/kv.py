"""KVManager: block tables, prefix cache, and the reservation ledger.

The middle layer of the decomposed engine (ISSUE 7). It owns the block
manager — :class:`~paddle_tpu.models.paged.RadixPrefixBlockManager`
(token-span radix trie, copy-on-write partial-block reuse) by default,
or the flat :class:`~paddle_tpu.models.paged.PrefixCachingBlockManager`
under the ``PT_RADIX_CACHE=0`` kill switch — plus the RESERVATION LEDGER the
admission discipline runs on: ``need[rid]`` is a request's worst-case
block count, ``resv[rid]`` the part not yet materialised as live table
entries, and ``reserved`` their sum — the blocks the free list must
keep clear of other requests. The scheduler decides WHO gets blocks;
this layer tracks what was promised.
"""
from __future__ import annotations

import os

from paddle_tpu.models.paged import (PrefixCachingBlockManager,
                                     RadixPrefixBlockManager)
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.serving.telemetry import (_PREFIX_EVICTIONS,
                                          _PREFIX_HIT_RATE, _PREFIX_HITS,
                                          _PREFIX_PARTIAL_HITS,
                                          _PREFIX_TOKEN_HIT_RATE,
                                          _PREFIX_TOKEN_HITS)


def cache_block_bytes(cache) -> int:
    """HBM bytes ONE pool block holds across all layers — K and V codes
    at their ACTUAL stored dtype, plus the parallel scale pools of a
    quantized cache (ISSUE 17). The memledger's bytes_per_token gauges
    divide by this, so an int8 pool reports its true (roughly halved)
    footprint instead of a bf16 assumption."""
    import numpy as np
    pools = (*cache.k_pools, *cache.v_pools,
             *getattr(cache, "k_scales", ()),
             *getattr(cache, "v_scales", ()))
    return sum(int(np.prod(p.shape[1:])) * p.dtype.itemsize for p in pools)


class KVManager:
    """Block allocation + worst-case reservation accounting."""

    def __init__(self, num_blocks: int, block_size: int):
        # refcounted + prefix-cached: beam groups share prompt blocks
        # copy-on-write; requests with equal prompt prefixes share the
        # prefix blocks outright (prefill only runs on the uncached
        # suffix); with no sharing it behaves exactly like BlockManager.
        # Default is the radix trie (token-span matching + partial-block
        # COW); PT_RADIX_CACHE=0 coerces back to the flat full-block
        # hash map (checked at construction — per engine)
        cls = (PrefixCachingBlockManager
               if os.environ.get("PT_RADIX_CACHE", "1") == "0"
               else RadixPrefixBlockManager)
        self.mgr = cls(num_blocks, block_size)
        # the block manager owns the per-pool memory ledger (its own
        # mutation choke points notify it); this layer mirrors the
        # reservation count into it and exposes the forensic wrappers
        self.ledger = self.mgr.ledger
        self.reserved = 0            # blocks promised to in-flight requests
        self.resv: dict[int, int] = {}    # req_id -> outstanding reserve
        self.need: dict[int, int] = {}    # req_id -> worst-case blocks
        self._prefix_pushed = dict(self.mgr.cache_stats)

    # --------------------------------------------------- pool passthroughs
    @property
    def num_blocks(self):
        return self.mgr.num_blocks

    @property
    def block_size(self):
        return self.mgr.block_size

    @property
    def free_blocks(self):
        return self.mgr.free_blocks

    @property
    def tables(self):
        return self.mgr.tables

    def blocks_needed(self, n_tokens: int) -> int:
        return self.mgr.blocks_needed(n_tokens)

    def allocate(self, rid: int, n_tokens: int):
        return self.mgr.allocate(rid, n_tokens)

    def free(self, rid: int):
        self.mgr.free(rid)

    # ------------------------------------------------------------- ledger
    def live_blocks(self, rid: int) -> int:
        """Blocks currently held (window recycling leaves None holes)."""
        return sum(b is not None for b in self.mgr.tables.get(rid, []))

    def begin(self, rid: int, need: int):
        """Open a ledger entry: worst case recorded, nothing held yet."""
        self.need[rid] = need
        self.resv[rid] = 0

    def hold(self, rid: int, n: int):
        """Set the outstanding reserve to ``n`` blocks (chunk-prefill and
        beam admissions hold their whole worst case up front)."""
        self.reserved += n - self.resv.get(rid, 0)
        self.resv[rid] = n
        self.ledger.set_reserved(self.reserved)

    def update(self, rid: int, live: int = None):
        """Outstanding reserve = worst case minus blocks currently held
        (recycling under a sliding window RETURNS headroom). Beam groups
        pass their deduplicated ``live`` count (shared prompt blocks
        appear in several beams' tables)."""
        if live is None:
            live = self.live_blocks(rid)
        new = max(0, self.need[rid] - live)
        self.reserved += new - self.resv[rid]
        self.resv[rid] = new
        self.ledger.set_reserved(self.reserved)

    def release(self, rid: int):
        """Close the ledger entry, returning its reserve to the pool."""
        self.reserved -= self.resv.pop(rid, 0)
        self.need.pop(rid, None)
        self.ledger.set_reserved(self.reserved)

    def headroom(self, rid: int = None) -> int:
        """Free blocks net of OTHER requests' standing reservations."""
        others = self.reserved - (self.resv.get(rid, 0) if rid is not None
                                  else 0)
        return self.free_blocks - max(0, others)

    # --------------------------------------------------- memory forensics
    def record_stall(self, need: int, slots_short: bool = False):
        """An admission was blocked at the headroom gate — attribute the
        missing blocks to the ledger state holding them."""
        self.ledger.record_stall(need, slots_short=slots_short)

    def take_peak(self, rid) -> int:
        """Pop the request's lifetime peak live-block count."""
        return self.ledger.take_peak(rid)

    def reconcile(self) -> dict:
        """Block-for-block walk of the manager vs the ledger mirrors
        (the per-tick invariant the chaos suites assert)."""
        return self.ledger.reconcile(self.mgr, reserved=self.reserved)

    # ----------------------------------------------------------- hygiene
    def assert_quiescent(self):
        """Every block back in the pool (prefix-cache parked blocks count
        — they are reclaimable), no standing reservations, no tables.
        Failure messages carry the ledger's state breakdown (which states
        hold the leaked blocks) and land in the flight ring."""
        try:
            assert self.mgr.free_blocks == self.mgr.num_blocks, (
                f"block leak: {self.mgr.num_blocks - self.mgr.free_blocks} "
                f"of {self.mgr.num_blocks} blocks unaccounted for")
            assert self.reserved == 0, f"reservation leak: {self.reserved}"
            assert not self.resv and not self.need, (
                f"ledger leak: resv={self.resv} need={self.need}")
            assert not self.mgr.tables, f"table leak: {list(self.mgr.tables)}"
        except AssertionError as e:
            FLIGHT.record("serving.quiescence_violation",
                          **self.ledger.flight_fields())
            raise AssertionError(
                f"{e} | kv ledger: {self.ledger.describe()}") from None

    def push_prefix_metrics(self):
        """Counters are process-global and cumulative; the manager's
        stats are per-engine — push only what this engine added since
        the last refresh."""
        stats = getattr(self.mgr, "cache_stats", None)
        if stats is None:
            return
        # stat keys added after construction (the radix trie grows the
        # dict) must delta against 0, not KeyError against the snapshot
        pushed = self._prefix_pushed

        def delta(key):
            return stats.get(key, 0) - pushed.get(key, 0)

        _PREFIX_HITS.inc(delta("hit_blocks"))
        _PREFIX_EVICTIONS.inc(delta("evictions"))
        _PREFIX_TOKEN_HITS.inc(delta("token_hits"))
        _PREFIX_PARTIAL_HITS.inc(delta("partial_hits"))
        self._prefix_pushed = dict(stats)
        _PREFIX_HIT_RATE.set(stats.get("hit_blocks", 0)
                             / max(stats.get("lookup_blocks", 0), 1))
        if stats.get("lookup_tokens", 0):
            _PREFIX_TOKEN_HIT_RATE.set(stats["token_hits"]
                                       / stats["lookup_tokens"])
