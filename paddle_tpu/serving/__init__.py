"""LLM serving: continuous-batching engine + multi-replica router.

The monolithic ``paddle_tpu/serving.py`` is now a package (ISSUE 7):

  * :mod:`.engine`    — ``LLMEngine``, the per-replica orchestrator
  * :mod:`.scheduler` — admission/deadlines/preemption/backpressure
  * :mod:`.kv`        — block tables, prefix cache, reservation ledger
  * :mod:`.executor`  — the jitted prefill/decode/verify programs
  * :mod:`.router`    — LOR dispatch over N replicas, session affinity,
                        health gating, disaggregated prefill/decode
  * :mod:`.transfer`  — the KV handoff seam between replicas
  * :mod:`.adapters`  — multi-tenant LoRA adapter store (device LRU)
  * :mod:`.grammar`   — token-mask automata for constrained decoding

Everything the old module exported is re-exported here, so
``from paddle_tpu.serving import LLMEngine, Request`` and every other
pre-split import keeps working unchanged.
"""
from paddle_tpu.models.decoding import KVCache, _sample_rows  # noqa: F401
from paddle_tpu.models.paged import (  # noqa: F401
    PagedKVCache, PrefixCachingBlockManager, PrefixMatch,
    RadixPrefixBlockManager, _beam_finalize,
    _BEAM_GROUP_UPDATE_JIT, _BEAM_SELECT_JIT, _PREFILL_CHUNK_JIT,
    _PREFILL_JIT, _REWIND_LENS_JIT, _TICK_JIT, _VERIFY_CHUNK_JIT,
    greedy_accept_length, is_moe_model, stochastic_accept_row)
from paddle_tpu.models.speculative import _FWD_ROWS_JIT  # noqa: F401
from paddle_tpu.observability import METRICS, span as _span  # noqa: F401
from paddle_tpu.observability.flight import FLIGHT  # noqa: F401
from paddle_tpu.utils.faults import fault_point  # noqa: F401

from paddle_tpu.serving.adapters import AdapterStore  # noqa: F401
from paddle_tpu.serving.degrade import (  # noqa: F401
    DegradationController, SessionSnapshot, default_signals)
from paddle_tpu.serving.engine import LLMEngine  # noqa: F401
from paddle_tpu.serving.grammar import (  # noqa: F401
    TokenMaskAutomaton, json_schema_regex)
from paddle_tpu.serving.executor import (  # noqa: F401
    ModelExecutor, _SAMPLE_ROWS_JIT)
from paddle_tpu.serving.kv import KVManager  # noqa: F401
from paddle_tpu.serving.router import Replica, Router  # noqa: F401
from paddle_tpu.serving.scheduler import Scheduler  # noqa: F401
from paddle_tpu.serving.telemetry import (  # noqa: F401
    _ACTIVE_SLOTS, _ADMITTED, _CANCELLED, _DRAIN, _FINISHED, _KV_IN_USE,
    _KV_UTIL, _MOE_DROPPED, _PREEMPTED, _PREFIX_EVICTIONS, _PREFIX_HIT_RATE,
    _PREFIX_HITS, _QUEUE_DEPTH, _QUEUE_WAIT, _R_DEATHS, _R_DISPATCH,
    _R_HEALTH, _R_OUTSTANDING, _R_REQUEUES, _R_TRANSFER_BLOCKS,
    _R_TRANSFERS, _REJECTED, _SPEC_ACCEPTED, _SPEC_FALLBACKS,
    _SPEC_PROPOSED, _SPEC_RATE, _SPEC_TOKENS, _TICK, _TIMEOUTS, _TOK_LAT,
    _TOKENS, _TTFT)
from paddle_tpu.serving.transfer import (  # noqa: F401
    DeviceKVTransfer, KVPayload, KVTransfer, KVTransferError,
    TransportPolicy, validate_payload)
from paddle_tpu.serving.types import (  # noqa: F401
    EngineDrainingError, OverloadError, QueueFullError, Request,
    _BeamGroup)

__all__ = [
    "LLMEngine", "Request", "QueueFullError", "EngineDrainingError",
    "OverloadError",
    "Router", "Replica", "Scheduler", "KVManager", "ModelExecutor",
    "KVTransfer", "DeviceKVTransfer", "KVPayload", "KVTransferError",
    "TransportPolicy", "validate_payload",
    "DegradationController", "SessionSnapshot", "default_signals",
    "AdapterStore", "TokenMaskAutomaton", "json_schema_regex",
]
