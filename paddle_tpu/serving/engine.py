"""Continuous-batching LLM serving engine.

Ref capability: PaddleNLP ``llm/predict/predictor.py`` block-attention
serving (request queue + block KV cache + ``fused_multi_transformer``'s
block cache ops). TPU-native split:

  * DEVICE — :class:`~paddle_tpu.serving.executor.ModelExecutor`: the
    fixed-shape jitted programs from ``models/paged.py`` (slot-aware
    prefill, chunked prefill/verify, the fused decode tick). Shapes
    never change across ticks, so nothing recompiles.
  * HOST — :class:`~paddle_tpu.serving.scheduler.Scheduler` (FCFS
    queue, deadlines, preemption policy, backpressure) and
    :class:`~paddle_tpu.serving.kv.KVManager` (block tables, prefix
    cache, the reservation ledger). All per-tick bookkeeping is
    vectorised numpy; the only per-tick device→host traffic is the
    [num_slots] sampled-token fetch.

``LLMEngine`` orchestrates the three: slot state lives here, policy in
the scheduler, block accounting in the KV manager, device state in the
executor. The pre-split attribute surface (``engine.mgr``,
``engine.queue``, ``engine._reserved``, ...) is preserved as
delegating properties — external callers and tests see the same API
the monolithic ``serving.py`` exposed.

Capacity discipline: a request is admitted only when the pool can cover
its WHOLE worst case (prompt + max_new_tokens) net of other in-flight
reservations — blocks are still allocated lazily (pool usage ≈ Σ live
lengths), but an admitted request can never hit an out-of-blocks
condition mid-decode (there is no preemption to recover with).

Multi-replica serving (ISSUE 7): ``prefill_only=True`` stops the tick
after chunked prefill — the replica admits and prefills but never
decodes; a :class:`~paddle_tpu.serving.router.Router` extracts each
finished sequence (``extract_sequence``) and installs it into a
decode-role replica (``install_sequence``) via the KV-transfer seam.
"""
from __future__ import annotations

import os
import time
from contextlib import contextmanager
from dataclasses import replace as _dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.paged import (_beam_finalize, _BEAM_SELECT_JIT,
                                     greedy_accept_length, is_moe_model,
                                     kv_quant_enabled,
                                     stochastic_accept_row)
from paddle_tpu.observability import span as _span
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.goodput import GOODPUT
from paddle_tpu.observability.requests import REQUESTS
from paddle_tpu.observability.roofline import (ModelGeometry,
                                               record_serving_throughput,
                                               resolve_serving_peaks)
from paddle_tpu.serving.executor import ModelExecutor, _SAMPLE_ROWS_JIT  # noqa: F401  (re-exported)
from paddle_tpu.serving.kv import KVManager, cache_block_bytes
from paddle_tpu.serving.scheduler import Scheduler
from paddle_tpu.serving.cp import (_CP_AXIS, _CP_GATHER_S,
                                   _CP_SHARD_BLOCKS, shard_occupancy)
from paddle_tpu.serving.degrade import SessionSnapshot
from paddle_tpu.serving.telemetry import (_ACTIVE_SLOTS, _ASYNC_DEPTH,
                                          _ASYNC_DRAINS, _CANCELLED,
                                          _DRAIN, _FINISHED,
                                          _GRAMMAR_SPEC_REJECTS,
                                          _GRAMMAR_TOKENS, _KV_IN_USE,
                                          _KV_UTIL, _QUEUE_DEPTH,
                                          _REJECTED, _SNAPSHOTS,
                                          _SPEC_ACCEPTED,
                                          _SPEC_DRAFT_REUSE,
                                          _SPEC_FALLBACKS,
                                          _SPEC_PROPOSED, _SPEC_RATE,
                                          _SPEC_TOKENS, _TENANT_FINISHED,
                                          _TENANT_REJECTED, _TENANT_TOK_LAT,
                                          _TENANT_TOKENS, _TENANT_TTFT,
                                          _TICK, _TICK_BREAKDOWN,
                                          _TICK_HIDDEN, _TIMEOUTS,
                                          _TOK_LAT, _TOKENS,
                                          _TTFT, tenant_label)
from paddle_tpu.serving.transfer import (KVPayload, _GATHER_BLOCKS_JIT,
                                         _INSTALL_BLOCKS_JIT)
from paddle_tpu.serving.types import (EngineDrainingError, OverloadError,
                                      QueueFullError, Request, _BeamGroup)
from paddle_tpu.utils.faults import fault_point
from paddle_tpu.utils.profiler import device_memory_stats


class LLMEngine:
    """Continuous-batching engine over a shared paged KV pool.

    ``num_slots`` concurrent sequences; queued requests are admitted
    MID-FLIGHT into slots freed by finished ones (prefill interleaves with
    decode ticks). ``step()`` is one engine tick; ``run()`` drains
    everything and returns {req_id: full token list}.
    """

    def __init__(self, model, *, num_slots=8, block_size=16,
                 max_prompt_len=128, max_seq_len=None, num_blocks=None,
                 eos_token_id=None, temperature=0.0, top_k=None, top_p=None,
                 seed=0, prefix_caching=True, preemption=False,
                 max_queue_len=None, clock=None, draft_model=None,
                 spec_k=4, spec_adaptive=True, prefill_only=False,
                 adapter_store=None, degrade=None, slo=None, kv_dtype=None,
                 cp=1, async_depth=0):
        cfg = model.cfg
        self.model = model
        # quantized KV cache (ISSUE 17): kv_dtype="int8" stores the block
        # pools as int8 with per-(position, kv-head) f32 scale pools.
        # PT_QUANT_KV=0 is the kill switch — checked HERE (construction)
        # so the engine falls back to model-dtype pools, and again at
        # trace time inside the quantize-on-write path, so a stale int8
        # trace can never silently run with the switch off.
        if kv_dtype is not None and not kv_quant_enabled():
            kv_dtype = None
        self.kv_dtype = kv_dtype
        # context-parallel serving (ISSUE 18): cp>1 shards the paged KV
        # pool's physical blocks over a cp-wide mesh; prefill partials
        # merge via ring/Ulysses and decode merges via psum. PT_CP=0 is
        # the kill switch — checked HERE (construction) so the engine
        # collapses to the single-device path with bit-identical traces.
        cp = int(cp)
        if cp != 1 and os.environ.get(
                "PT_CP", "1").strip().lower() in ("0", "off", "false"):
            cp = 1
        if cp < 1:
            raise ValueError(f"cp must be >= 1, got {cp}")
        self.cp = cp
        # async pipelined decode (ISSUE 20): async_depth=K keeps up to K
        # decode ticks dispatched-but-unfetched; the tick's output token
        # array stays ON DEVICE feeding the next tick's last_tok while
        # the previous tick's tokens are fetched/emitted on the host,
        # hidden under the in-flight dispatch (PR 3's deferred-sync
        # contract, serving-side). PT_ASYNC_DECODE=0 is the kill switch —
        # checked HERE (construction) so depth collapses to 0 and the
        # engine traces EXACTLY the synchronous pre-PR programs.
        async_depth = int(async_depth)
        if async_depth and os.environ.get(
                "PT_ASYNC_DECODE", "1").strip().lower() in (
                    "0", "off", "false"):
            async_depth = 0
        if async_depth < 0:
            raise ValueError(
                f"async_depth must be >= 0, got {async_depth}")
        self.async_depth = async_depth
        self.num_slots = num_slots
        self.block_size = block_size
        # graceful degradation (ISSUE 16): an optional shared
        # DegradationController — consulted by the spec gate, the
        # chunked-prefill budget, admission shedding, and the session
        # gate. None (the default) means full service, always.
        self.degrade = degrade
        # per-tenant SLO tracking + usage metering (ISSUE 19): an
        # optional shared SLOTracker — charged per tick from step(),
        # polled from the gauge sweep. None means no tracking, ever.
        self.slo = slo
        self.max_prompt_len = max_prompt_len
        self.max_seq_len = max_seq_len or (max_prompt_len + 256)
        self.max_blocks_per_seq = -(-self.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = num_slots * self.max_blocks_per_seq
        # MoE models route tokens through expert all_to_alls inside the
        # tick — give chaos a hook at that boundary (dead expert shard)
        self._is_moe = is_moe_model(model)
        if self.cp > 1:
            if self._is_moe:
                raise NotImplementedError(
                    "context-parallel serving (cp>1) does not compose with "
                    "MoE models yet — the expert all_to_all would need its "
                    "own mesh axis")
            if adapter_store is not None:
                raise NotImplementedError(
                    "context-parallel serving (cp>1) does not compose with "
                    "multi-LoRA (adapter_store) yet — per-slot adapter "
                    "gathers are not sharded over cp")
            # each shard owns num_blocks/cp physical blocks — round the
            # pool up so the contiguous split is exact
            num_blocks = -(-num_blocks // self.cp) * self.cp
        self.eos_token_id = eos_token_id
        # engine defaults; each request may override temperature/top_p
        # (top_k stays engine-global — it is a static compile parameter)
        self.default_temp = float(temperature)
        self.default_top_p = 1.0 if top_p is None else float(top_p)
        self.top_k = top_k
        self.temps = np.zeros(num_slots, np.float32)
        self.top_ps = np.ones(num_slots, np.float32)
        # sliding-window models: blocks entirely below cur - window are
        # never attended again (the paged kernel KEEPS only positions
        # >= lens - window, masking everything below) — recycle them,
        # bounding live blocks per sequence by O(window), not O(length)
        self.window = getattr(cfg, "sliding_window", None)
        self._dyn_rope = (getattr(cfg, "rope_scaling", None)
                          or {}).get("type") == "dynamic"
        # prefix caching is sound only when a block's KV is a function of
        # its token prefix alone: windowed recycling punches holes in the
        # table, and dynamic-NTK makes KV depend on the FULL prompt length
        self.prefix_caching = bool(prefix_caching) and self.window is None \
            and not self._dyn_rope
        # preemption: admit optimistically (no worst-case reservation for
        # greedy requests; beams keep theirs) and, on out-of-blocks,
        # preempt the youngest greedy slot — it re-queues with
        # resume-prompt = prompt + generated-so-far and recomputes
        self.preemption = bool(preemption)
        # prefill-role replica (disaggregated serving): the tick stops
        # after chunked prefill — slots activate with their first token
        # but NEVER decode here; the router extracts and ships them
        self.prefill_only = bool(prefill_only)
        # replica name for request-tracker events; the Router stamps the
        # replica name here so cross-replica timelines stitch (ISSUE 9)
        self.trace_name = None

        # ---- speculative decoding (ISSUE 5): draft-and-verify tick ----
        # ``draft_model`` enables it; each eligible slot drafts up to
        # spec_k tokens through a per-slot dense draft cache, then ONE
        # batched target chunk forward verifies them through the paged
        # pool. PT_SPEC_DECODE=0 is the kill switch (checked every tick,
        # so it also disables a live engine); beam slots always take the
        # one-token path.
        self.draft_model = draft_model
        self.spec_k = int(spec_k)
        self.spec_adaptive = bool(spec_adaptive)
        if draft_model is not None:
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if self.window is not None or \
                    getattr(draft_model.cfg, "sliding_window", None):
                raise NotImplementedError(
                    "speculative decoding needs full (un-windowed) caches "
                    "on both models — rewind relies on masked stale KV")
            if self._dyn_rope:
                raise NotImplementedError(
                    "speculative decoding with dynamic-NTK rope is not "
                    "supported (the verify chunk shares the chunked-"
                    "prefill forward, which refuses per-chunk bases)")
            if draft_model.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}")
            # host RNG for draft sampling + accept/reject (temperature>0):
            # the accept rule preserves the target distribution for any
            # uniform source, so this stream need not match the engine key
            self._spec_rs = np.random.RandomState((seed ^ 0x5eed) & 0x7fffffff)

        # ---- the three extracted layers ----
        self.kv = KVManager(num_blocks, block_size)
        self._block_bytes = None     # per-block HBM bytes, lazily computed
        self._dev_mem_t = None       # last device_memory_stats refresh
        self.sched = Scheduler(max_queue_len=max_queue_len, clock=clock)
        self.exe = ModelExecutor(
            model, num_slots=num_slots, num_blocks=num_blocks,
            block_size=block_size, max_blocks_per_seq=self.max_blocks_per_seq,
            top_k=top_k, seed=seed, draft_model=draft_model,
            spec_k=self.spec_k, max_seq_len=self.max_seq_len,
            kv_dtype=kv_dtype, cp=self.cp)

        # host mirrors (vectorised bookkeeping — no per-token python loops)
        self.slot_req = np.full(num_slots, -1, np.int64)   # req_id or -1
        self.active = np.zeros(num_slots, bool)
        self.cur = np.zeros(num_slots, np.int64)     # tokens stored in cache
        self.gen = np.zeros(num_slots, np.int64)     # tokens generated
        self.max_gen = np.zeros(num_slots, np.int64)
        self.table_len = np.zeros(num_slots, np.int64)
        self.last_tok = np.zeros(num_slots, np.int32)

        # ---- multi-tenant serving (ISSUE 14) ----
        # ``adapter_store``: a shared AdapterStore; a request carrying an
        # adapter_id is admitted only once its adapter is device-resident
        # AND pinned (the scheduler acquires it), and every per-slot
        # forward adds the grouped rank-r correction for that slot's
        # cache index. PT_MULTILORA=0 is the kill switch: with it off —
        # or with no store, or no adapter-carrying rows — the forwards
        # are handed lora=None and trace EXACTLY the base programs.
        self.adapter_store = adapter_store
        self.slot_aidx = np.full(num_slots, -1, np.int64)  # cache idx / -1
        self._adapter_pins: dict[int, object] = {}   # rid -> adapter_id
        # grammar-constrained decoding: slot -> [automaton, state]. The
        # state advances in ``_emit`` as tokens commit, so it is always
        # the state AFTER everything in req.tokens — a pure function of
        # the emitted stream (resume/install replays it).
        self._grammar: dict[int, list] = {}

        # spec-decode per-slot state (allocated tiny even when spec is
        # off, so reset sites need no guards). ``draft_cur``: committed-
        # sequence positions 0..draft_cur-1 are in the draft cache — 0
        # means empty, which is how eviction "frees" a draft cache and
        # replay rebuilds it (the re-admitted slot re-feeds from scratch).
        self.draft_cur = np.zeros(num_slots, np.int64)
        self.slot_k = np.full(num_slots, self.spec_k, np.int64)
        self._acc_ema = np.ones(num_slots, np.float64)
        # draft-cache reuse across sessions of a slot (ISSUE 11): the
        # token ids whose K/V currently sit in the draft cache rows
        # 0..draft_cur-1, snapshotted host-side at each commit. A new
        # request whose radix-adopted prefix matches the resident ids
        # seeds draft_cur past the match instead of re-feeding from 0.
        self._draft_resident: dict[int, np.ndarray] = {}
        # per-slot adopted span of the CURRENT request: the draft
        # catch-up feed bills only re-embeds inside this span as
        # replay_prefill waste (first-time prompt embedding is not waste)
        self._adopted_span = np.zeros(num_slots, np.int64)

        self.is_beam = np.zeros(num_slots, bool)
        self.groups: dict[int, _BeamGroup] = {}
        self._sid_counter = 0        # unique fork keys: (req_id, counter)
        # chunked prefill (prompts > max_prompt_len): rid -> (slot,
        # tokens consumed); slots stay inactive until the last chunk
        self.prefilling: dict[int, tuple] = {}

        self._staged_admits = frozenset()   # this tick's pre-scatter rows
        # host-vs-device split of decode ticks (admission ticks excluded):
        # stats["host_s"] is scheduling/bookkeeping, stats["device_s"] the
        # jitted tick incl. the [num_slots] token fetch
        self.stats = {"host_s": 0.0, "device_s": 0.0, "ticks": 0,
                      "preemptions": 0, "timeouts": 0, "cancelled": 0,
                      "rejected": 0, "spec_ticks": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_fallbacks": 0}
        self._adm_counter = 0                # admission recency, per slot
        self.adm_order = np.zeros(num_slots, np.int64)

        # ---- roofline ledger (ISSUE 12): cumulative per-phase
        # [seconds, tokens, weight passes, KV-read positions], folded
        # into serving_mfu/mbu/arith_intensity at each gauge sweep.
        # Peaks resolve once from device 0 (0.0 off-TPU → gauges read
        # 0.0 = undefined; PT_ROOFLINE_KIND overrides for what-if).
        # _tick_phase holds the CURRENT tick's wall-time split; step()
        # folds it into the breakdown histogram and these accumulators.
        def _geom(m, cache=None):
            try:
                g = ModelGeometry.from_config(
                    m.cfg, dtype_bytes=jnp.dtype(m.cfg.dtype).itemsize)
            except Exception:
                return None      # adapter without a full config: no ledger
            # quantized serving (ISSUE 17): bill the ACTUAL storage
            # dtypes — int8 pools carry 1-byte codes + a 4-byte
            # per-(position, kv-head) scale, weight-only models stream
            # bits/8 bytes per param — or MBU would be overstated 2x
            kw = {}
            if cache is not None and getattr(cache, "k_scales", ()):
                kw.update(kv_dtype_bytes=cache.k_pools[0].dtype.itemsize,
                          kv_scale_bytes=4)
            bits = getattr(m, "_wo_bits", None)
            if bits:
                kw["weight_dtype_bytes"] = bits / 8.0
            # context parallelism (ISSUE 18): bill the per-token
            # cross-shard merge traffic in the decode bytes model
            if self.cp > 1 and cache is not None:
                kw["cp"] = self.cp
            return _dc_replace(g, **kw) if kw else g
        self._geom = _geom(model, self.exe.cache)
        self._draft_geom = _geom(draft_model) if draft_model is not None \
            else None
        try:
            dev0 = jax.devices()[0]
        except Exception:
            dev0 = None
        self._peak_flops, self._peak_hbm = resolve_serving_peaks(dev0)
        self._phase_acc = {p: [0.0, 0, 0, 0] for p in
                           ("prefill", "decode", "spec_draft", "spec_verify")}
        self._tick_phase: dict[str, float] = {}

        # ---- async pipeline window (ISSUE 20) ----
        # _async_win: oldest-first list of dispatched-but-unfetched ticks,
        # each {"nxt": device tokens, "ran": device mask, "rng_before":
        # the executor rng BEFORE that tick's split}. _async_dev holds
        # the device-resident loop state (tokens/stop/gen/max_gen/active)
        # threading tick N's outputs into tick N+1 without a host round
        # trip; None whenever the window is empty. _async_rewound guards
        # the one-shot rng rewind when draining a fully-masked tick.
        self._async_win: list[dict] = []
        self._async_dev = None
        self._async_rewound = False
        self._async_draining = False
        # gauge-sweep throttle (PT_GAUGE_EVERY_S): wall-clock of the last
        # sweep, a force flag set at drain/finish boundaries so run()-end
        # gauges are exact, and a sweep counter the bench leg reads.
        self._gauge_t = None
        self._gauge_force = False
        self._gauge_sweeps = 0
        # hidden host time accumulated this tick (drain work overlapped
        # with in-flight device dispatch); observed once per step().
        self._hidden_acc = 0.0
        # spec-decode D2H accounting: bytes fetched by pick_all this
        # engine lifetime (satellite: non-greedy rows gathered on device)
        self._spec_fetch_bytes = 0

    # ------------------------------------------- pre-split attribute surface
    # The monolithic serving.py exposed all of this directly on the
    # engine; tests and external callers still poke it, so every moved
    # field delegates to the layer that now owns it.
    @property
    def mgr(self):
        return self.kv.mgr

    @property
    def queue(self):
        return self.sched.queue

    @property
    def requests(self):
        return self.sched.requests

    @property
    def cache(self):
        return self.exe.cache

    @cache.setter
    def cache(self, value):
        self.exe.cache = value

    @property
    def rng(self):
        return self.exe.rng

    @rng.setter
    def rng(self, value):
        self.exe.rng = value

    @property
    def _draft_cache(self):
        return self.exe._draft_cache

    @_draft_cache.setter
    def _draft_cache(self, value):
        self.exe._draft_cache = value

    @property
    def _reserved(self):
        return self.kv.reserved

    @_reserved.setter
    def _reserved(self, value):
        self.kv.reserved = value

    @property
    def _resv(self):
        return self.kv.resv

    @property
    def _need(self):
        return self.kv.need

    @property
    def _draining(self):
        return self.sched.draining

    @_draining.setter
    def _draining(self, value):
        self.sched.draining = value

    @property
    def max_queue_len(self):
        return self.sched.max_queue_len

    @max_queue_len.setter
    def max_queue_len(self, value):
        self.sched.max_queue_len = value

    @property
    def _clock(self):
        return self.sched.clock

    @_clock.setter
    def _clock(self, value):
        self.sched.clock = value

    @property
    def _has_deadlines(self):
        return self.sched.has_deadlines

    @_has_deadlines.setter
    def _has_deadlines(self, value):
        self.sched.has_deadlines = value

    # ------------------------------------------------------------- intake
    def add_request(self, req: Request) -> int:
        self.sched.check_backpressure(self.stats)
        # ladder L4: explicit backpressure on NEW sessions. Requests a
        # Router already accepted (_preadmitted) pass — rejecting them
        # here would double-gate dispatches and death requeues.
        if (self.degrade is not None and not req._preadmitted
                and not self.degrade.accepting_sessions()):
            self.stats["rejected"] += 1
            _REJECTED.inc(reason="degraded")
            if req.tenant_id is not None:
                _TENANT_REJECTED.inc(tenant=tenant_label(req.tenant_id))
            raise OverloadError(
                "degradation ladder at L4 — new sessions rejected, "
                "retry after the cluster recovers")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself produces the first token)")
        if req.num_beams < 1:
            raise ValueError("num_beams must be >= 1")
        if req.num_beams > 1:
            if req.num_beams > self.num_slots:
                raise ValueError(f"num_beams {req.num_beams} exceeds "
                                 f"num_slots={self.num_slots}")
            if self.cp > 1:
                raise NotImplementedError(
                    "beam search under context parallelism (cp>1) is not "
                    "supported — the beam select needs full logprobs, "
                    "which the cp tick does not gather")
            if self.window is not None:
                raise NotImplementedError(
                    "beam search + sliding-window block recycling are not "
                    "combined (a recycled parent block may be needed by a "
                    "forked child)")
            if req.stream is not None:
                raise ValueError("streaming is not supported for beam "
                                 "requests (tokens are only known at the "
                                 "final selection)")
        if len(req.prompt) < 1:
            raise ValueError("prompt must contain at least one token "
                             "(an empty row has no logit to sample from)")
        if len(req.prompt) > self.max_prompt_len and req.num_beams > 1:
            raise ValueError(f"prompt length {len(req.prompt)} exceeds "
                             f"max_prompt_len={self.max_prompt_len} "
                             "(chunked prefill does not combine with "
                             "beam search)")
        if len(req.prompt) > self.max_prompt_len and self.window is not None:
            raise NotImplementedError(
                "chunked prefill + sliding-window recycling not combined")
        if len(req.prompt) > self.max_prompt_len and \
                (getattr(self.model.cfg, "rope_scaling", None)
                 or {}).get("type") == "dynamic":
            # refuse HERE: a trace-time raise inside step() would leave
            # the slot claimed and the request wedged in self.prefilling
            raise NotImplementedError(
                "chunked prefill with dynamic-NTK rope is not supported")
        if len(req.prompt) + req.max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        if self._worst_case_blocks(req) > self.mgr.num_blocks:
            # the request could NEVER be admitted — even a cp-scaled pool
            # (num_blocks grows ~linearly with the cp axis) cannot hold
            # its worst case. Finish it gracefully instead of raising:
            # a raise here would be fine for this caller, but the same
            # check used to wedge router/batch clients that submit
            # blindly — surface finish_reason="too_long" through the
            # normal completion path so the FCFS head never jams on it.
            rid = self.sched.enqueue(req)
            self.queue.pop()                  # never actually waits
            REQUESTS.submit(req, source="engine")
            req.done = True
            req.finish_reason = "too_long"
            self.stats["rejected"] += 1
            _REJECTED.inc(reason="too_long")
            _FINISHED.inc(reason="too_long")
            if req.tenant_id is not None:
                _TENANT_REJECTED.inc(tenant=tenant_label(req.tenant_id))
                _TENANT_FINISHED.inc(tenant=tenant_label(req.tenant_id),
                                     reason="too_long")
            FLIGHT.record("serving.reject", rid=rid, reason="too_long")
            REQUESTS.finish(req, "too_long", replica=self.trace_name)
            return rid
        if req.adapter_id is not None:
            if self.adapter_store is None:
                raise ValueError(
                    "request carries an adapter_id but the engine was "
                    "built without an adapter_store")
            if not self.adapter_store.known(req.adapter_id):
                raise ValueError(f"adapter {req.adapter_id!r} is not "
                                 "registered with the adapter store")
            if req.num_beams > 1:
                raise NotImplementedError(
                    "multi-LoRA + beam search are not combined")
        if req.grammar is not None:
            if req.num_beams > 1:
                raise NotImplementedError(
                    "grammar-constrained decoding + beam search are not "
                    "combined (beam tokens come from the select, not "
                    "the sampler)")
            if not (hasattr(req.grammar, "bias")
                    and hasattr(req.grammar, "advance")):
                raise ValueError("req.grammar must be a "
                                 "serving.grammar.TokenMaskAutomaton")
            if len(req.grammar.vocab) != self.model.cfg.vocab_size:
                raise ValueError(
                    f"grammar vocab {len(req.grammar.vocab)} != model "
                    f"vocab {self.model.cfg.vocab_size}")
        rid = self.sched.enqueue(req)
        REQUESTS.submit(req, source="engine")        # idempotent re-submit
        REQUESTS.event(req, "queued", replica=self.trace_name,
                       depth=len(self.queue))
        _QUEUE_DEPTH.set(len(self.queue))
        return rid

    def pop_finished(self) -> dict:
        """Remove and return completed requests ({req_id: Request}) — call
        periodically from a long-running serve loop so the engine does not
        retain every finished request's token list forever."""
        return self.sched.pop_finished()

    def generate(self, prompt, **kw) -> int:
        return self.add_request(Request(prompt, **kw))

    def has_work(self) -> bool:
        return (bool(self.queue) or bool(self.active.any())
                or bool(self.groups) or bool(self.prefilling)
                or bool(self._async_win))

    def outstanding(self) -> int:
        """Requests accepted but not yet finished (queued, prefilling, or
        decoding) — the router's least-outstanding-requests load signal."""
        return sum(1 for r in self.requests.values() if not r.done)

    # --------------------------------------------- cancellation/deadlines
    def _release_ledger(self, rid: int):
        self.kv.release(rid)

    def cancel(self, req_id: int, reason: str = "cancelled") -> bool:
        """Terminate a request wherever it currently lives — queued,
        chunk-prefilling, decoding, or mid-beam — freeing its blocks,
        reservation, and slot(s). Exception-atomic: every mutation below
        is a host dict/array op ordered so a failure cannot strand
        half-released state. Safe between ``step()`` calls (and from
        stream callbacks: an emptied slot is skipped by ``_emit``).
        Returns False for unknown/finished requests."""
        req = self.requests.get(req_id)
        if req is None or req.done:
            return False
        # in-flight async ticks may already hold this request's next
        # tokens: drain so the emitted stream (and the ledger) is exact
        # before its slot state is torn down. The drain can finish the
        # request (EOS/length in the window) — re-check afterwards.
        self._drain_async("cancel")
        if req.done:
            return False
        g = self.groups.get(req_id)
        sids = list(g.sid.values()) if g is not None else None
        if not self._detach(req_id):
            return False                            # mid-transition: punt
        self._release_ledger(req_id)
        # peak attribution survives the free above (the ledger keeps a
        # request's lifetime max past its table drop)
        peak = (sum(self.kv.take_peak(s) for s in sids) if sids
                else self.kv.take_peak(req_id))
        REQUESTS.event(req, "kv_peak", replica=self.trace_name, blocks=peak)
        req.done = True
        req.finish_reason = reason
        self.stats["timeouts" if reason == "timeout" else "cancelled"] += 1
        (_TIMEOUTS if reason == "timeout" else _CANCELLED).inc()
        _FINISHED.inc(reason=reason)
        if req.tenant_id is not None:
            _TENANT_FINISHED.inc(tenant=tenant_label(req.tenant_id),
                                 reason=reason)
        FLIGHT.record("serving.timeout" if reason == "timeout"
                      else "serving.cancel", rid=req_id)
        REQUESTS.finish(req, reason, replica=self.trace_name)
        return True

    def _detach(self, req_id: int) -> bool:
        """Free a live request's slot(s)/blocks wherever it currently is
        (queue, chunk prefill, beam group, active slot) WITHOUT touching
        the ledger or finishing it. Shared by cancel and the router's
        pull-back path. Returns False when the request holds nothing
        (unknown, or mid-transition)."""
        for i, q in enumerate(self.queue):          # still waiting
            if q.req_id == req_id:
                del self.queue[i]
                return True
        if req_id in self.prefilling:
            slot, _ = self.prefilling.pop(req_id)
            self.mgr.free(req_id)
            self.slot_req[slot] = -1
            self._release_adapter(req_id)
            return True
        if req_id in self.groups:
            g = self.groups.pop(req_id)
            for sid in g.sid.values():
                self.mgr.free(sid)
            for slot in g.slots:
                self.active[slot] = False
                self.is_beam[slot] = False
                self.slot_req[slot] = -1
            return True
        slots = np.nonzero(self.slot_req == req_id)[0]
        if not len(slots):
            return False
        slot = int(slots[0])
        self.mgr.free(req_id)
        self.active[slot] = False
        self.slot_req[slot] = -1
        self.draft_cur[slot] = 0
        self.slot_aidx[slot] = -1
        self._grammar.pop(slot, None)
        self._release_adapter(req_id)
        return True

    def release_request(self, rid: int):
        """Pull a live request OUT of the engine (router rebalancing /
        replica death): free its slot(s), blocks, and reservation, and
        forget it — WITHOUT marking it done. Returns the Request (with
        whatever tokens it generated) so the caller can re-dispatch it,
        or None for unknown/finished/mid-transition requests."""
        req = self.requests.get(rid)
        if req is None or req.done:
            return None
        self._drain_async("boundary")
        if req.done:
            return None
        g = self.groups.get(rid)
        sids = list(g.sid.values()) if g is not None else None
        if not self._detach(rid):
            return None
        self._release_ledger(rid)
        # the request leaves this engine: stamp its peak here (the next
        # replica's incarnation stamps its own; the summary takes the max)
        peak = (sum(self.kv.take_peak(s) for s in sids) if sids
                else self.kv.take_peak(rid))
        REQUESTS.event(req, "kv_peak", replica=self.trace_name, blocks=peak)
        return self.sched.release(rid)

    def _expire(self):
        self.sched.expire(self.cancel)

    def drain(self, cancel_queued: bool = False) -> dict:
        """Graceful shutdown: stop admitting (``add_request`` raises
        EngineDrainingError) but finish everything in flight; returns
        {req_id: tokens} like ``run``. ``cancel_queued=True`` also
        cancels requests still waiting for admission instead of running
        them to completion."""
        t0 = time.monotonic()
        with _span("serving.drain", cancel_queued=cancel_queued):
            self._draining = True
            if cancel_queued:
                for r in list(self.queue):
                    self.cancel(r.req_id)
            while self.has_work():
                self.step()
            self._refresh_gauges(force=True)
        _DRAIN.observe(time.monotonic() - t0)
        return {rid: r.tokens for rid, r in self.requests.items()}

    def assert_quiescent(self):
        """Invariant check once idle: every block is back in the pool
        (prefix-cache parked blocks count — they are reclaimable), no
        standing reservations, no per-sequence tables. Chaos tests call
        this after driving fault schedules: any leak in a recovery path
        shows up here as missing blocks."""
        assert not self.has_work(), "engine still has work"
        self.kv.assert_quiescent()
        assert not self._adapter_pins, \
            f"adapter pin leak: {self._adapter_pins}"

    def _pr(self, req) -> np.ndarray:
        """Effective prompt: the resume form (original prompt + tokens
        generated before a preemption), the original prompt otherwise."""
        return req.prompt if req._resume is None else req._resume

    def _remaining(self, req) -> int:
        """max_new_tokens still to generate (tokens survive preemption)."""
        return req.max_new_tokens - len(req.tokens)

    def _worst_case_blocks(self, req) -> int:
        """Blocks a request can ever hold at once. Windowed models recycle
        below-window blocks, so the live span is bounded by the window
        (plus the write-frontier block) — but prefill scatters the WHOLE
        prompt before any recycling, so that is a floor.

        Beam requests (K slots): shared prompt blocks once, plus per beam
        the generated span (straddling ≤ ceil(new/bs)+1 blocks), plus 2
        per beam for the copy-on-write partial forks (one held, one
        transient while the new fork exists before the parent is freed)."""
        p = len(self._pr(req))
        if req.num_beams > 1:
            k = req.num_beams
            return (self.mgr.blocks_needed(p)
                    + k * (self.mgr.blocks_needed(
                        req.max_new_tokens + self.block_size) + 2))
        total = p + self._remaining(req)
        if self.window is None:
            return self.mgr.blocks_needed(total)
        live = self.mgr.blocks_needed(
            min(total, self.window + 2 * self.block_size))
        return max(self.mgr.blocks_needed(p), live)

    # --------------------------------------- multi-LoRA / grammar state
    def _multilora_on(self) -> bool:
        """PT_MULTILORA=0 kill switch (checked per use, so it also
        disables a live engine): off — or no store — means every forward
        gets lora=None and traces the exact base program."""
        return (self.adapter_store is not None
                and os.environ.get("PT_MULTILORA", "1") != "0")

    def _release_adapter(self, rid: int):
        """Drop the ref-count pin the scheduler took at admission (idempotent
        — every detach/finish/preempt path calls it)."""
        aid = self._adapter_pins.pop(rid, None)
        if aid is not None and self.adapter_store is not None:
            self.adapter_store.release(aid)

    def _req_aidx(self, req) -> int:
        """Cache index of the request's pinned adapter (-1 = base path).
        Pinned entries are never evicted, so the index is stable for the
        request's whole slot tenure."""
        if req.req_id in self._adapter_pins and self._multilora_on():
            return self.adapter_store.index_of(req.adapter_id)
        return -1

    def _lora_arg(self, aidx, width: int):
        """The per-row lora pytree ``models.paged._lora_delta`` consumes,
        or None when no row carries an adapter (the None path traces the
        exact base program — bit-exactness by construction). ``aidx``:
        per-row cache index (-1 = base); ``width``: padded tokens per row
        in the forward — rows are contiguous token spans after the
        perm+reshape, so group sizes are row-counts times width."""
        if not self._multilora_on():
            return None
        aidx = np.asarray(aidx, np.int64)
        if not (aidx >= 0).any():
            return None
        cap = self.adapter_store.capacity
        order = np.argsort(np.where(aidx < 0, cap, aidx), kind="stable")
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        gs = np.bincount(aidx[aidx >= 0], minlength=cap) * width
        lora = self.adapter_store.stacks()
        lora["perm"] = jnp.asarray(order, jnp.int32)
        lora["inv"] = jnp.asarray(inv, jnp.int32)
        lora["gs"] = jnp.asarray(gs, jnp.int32)
        lora["aidx"] = jnp.asarray(aidx, jnp.int32)
        return lora

    def _bind_grammar(self, slot: int, req):
        """(Re)bind a slot's grammar state at activation. The state is a
        pure function of the emitted tokens, so a resume or an install
        replays ``req.tokens`` — preemption cannot drift the mask."""
        if req.grammar is None:
            self._grammar.pop(slot, None)
            return
        st = req.grammar.start_state
        for t in req.tokens:
            st = req.grammar.advance(st, int(t))
        self._grammar[slot] = [req.grammar, st]

    def _grammar_bias_rows(self, rows_slots, n_rows: int):
        """[n_rows, V] logit bias (0 / -1e30) for the listed (row, slot)
        pairs; None when no listed slot is grammar-bound — the sampler
        then traces its unbiased program, bit-identical to pre-grammar."""
        bound = [(i, s) for i, s in rows_slots if s in self._grammar]
        if not bound:
            return None
        bias = np.zeros((n_rows, self.model.cfg.vocab_size), np.float32)
        for i, s in bound:
            aut, st = self._grammar[s]
            bias[i] = aut.bias(st)
        return bias

    # ---------------------------------------------------------- admission
    def _admit(self):
        return self.sched.select_admissions(self)

    def _live_blocks(self, rid: int) -> int:
        return self.kv.live_blocks(rid)

    def _update_resv(self, rid: int):
        self.kv.update(rid)

    def _recycle_window(self, slots):
        """Free blocks entirely below cur - window for the given slots —
        live blocks per sequence stay O(window). Host-only: the paged
        kernel masks every position BELOW lens - window, so stale table
        entries pointing at recycled (even reused) blocks are never
        read."""
        for slot in slots:
            rid = int(self.slot_req[slot])
            dead = int(max(0, self.cur[slot] - self.window)
                       ) // self.block_size
            if dead > 0 and self.mgr.free_prefix(rid, dead):
                self._update_resv(rid)

    def _prefill(self, admits, beam_admits=()):
        """ONE padded prefill forward for every prompt admitted this tick —
        greedy prompts in rows 0..n-1, each beam request's prompt as one
        more row (written into its beam-0 slot; the forks are installed
        after, in ``_beam_init``)."""
        if not admits and not beam_admits:
            # nothing admitted: never pay the full (num_slots,
            # max_prompt_len) padded forward on all-sentinel rows
            return []
        a_cap = self.num_slots           # one compiled admission shape
        ids = np.zeros((a_cap, self.max_prompt_len), np.int32)
        lens = np.zeros(a_cap, np.int32)
        slots = np.full(a_cap, self.num_slots, np.int32)   # sentinel = drop
        rows = np.full((a_cap, self.max_blocks_per_seq),
                       self.mgr.num_blocks, np.int32)
        for i, (slot, req) in enumerate(admits):
            p = self._pr(req)
            ids[i, :len(p)] = p
            lens[i] = len(p)
            slots[i] = slot
            t = self.mgr.tables[req.req_id]
            rows[i, :len(t)] = t
            self.slot_req[slot] = req.req_id
            self.active[slot] = True
            self.cur[slot] = len(p)
            self.gen[slot] = 0
            self.max_gen[slot] = self._remaining(req)
            self._adm_counter += 1
            self.adm_order[slot] = self._adm_counter
            self.table_len[slot] = len(t)
            self.temps[slot] = (self.default_temp if req.temperature is None
                                else req.temperature)
            self.top_ps[slot] = (self.default_top_p if req.top_p is None
                                 else req.top_p)
            self.slot_aidx[slot] = self._req_aidx(req)
            self._bind_grammar(slot, req)
            # fresh draft state unless the resident draft cache covers a
            # radix-adopted prefix (an evicted slot's draft cache was
            # "freed" by zeroing this frontier — replay rebuilds it)
            self._seed_draft(slot, req)
            self.slot_k[slot] = self.spec_k
            self._acc_ema[slot] = 1.0
            REQUESTS.event(req, "prefill", replica=self.trace_name,
                           slot=slot, tokens=int(lens[i]))
        n = len(admits)
        beams = []
        self._staged_admits = frozenset(r.req_id for _, r in admits)
        for bi, (bslots, req) in enumerate(beam_admits):
            g, grows, csrc, cdst = self._beam_alloc(bslots, req)
            i = n + bi                   # every admit holds >= 1 slot, so
            ids[i, :g.s] = req.prompt    # greedy + beam rows fit in a_cap
            lens[i] = g.s
            slots[i] = bslots[0]
            rows[i] = grows[0]
            beams.append((g, grows, csrc, cdst))
        row_aidx = np.full(a_cap, -1, np.int64)
        for i, (slot, _) in enumerate(admits):
            row_aidx[i] = self.slot_aidx[slot]
        logits = self.exe.prefill(
            ids, lens, slots, rows,
            lora=self._lora_arg(row_aidx, self.max_prompt_len))
        self._staged_admits = frozenset()   # scatter landed: evictable again
        # roofline: one weight pass; prompts attend causally from offset 0
        self._acc_phase("prefill", int(lens.sum()), 1,
                        self._ctx_causal(lens, np.zeros_like(lens)))
        row_temps = np.zeros(a_cap, np.float32)
        row_tps = np.ones(a_cap, np.float32)
        for i, (slot, req) in enumerate(admits):
            row_temps[i] = self.temps[slot]
            row_tps[i] = self.top_ps[slot]
        first = self.exe.sample(
            logits, row_temps, row_tps,
            bias=self._grammar_bias_rows(
                [(i, slot) for i, (slot, _) in enumerate(admits)], a_cap))
        if self.window is not None:
            # a long prompt's below-window blocks die the moment prefill
            # has scattered them — and from here on the sequence can never
            # hold more than the window live bound, so relax its
            # reservation too (the prompt-size floor only mattered DURING
            # prefill)
            self._recycle_window([slot for slot, _ in admits])
            live_bound = self.mgr.blocks_needed(
                self.window + 2 * self.block_size)
            for slot, req in admits:
                rid = req.req_id
                self.kv.need[rid] = min(self.kv.need[rid], live_bound)
                self._update_resv(rid)
        emitted = []
        for i, (slot, req) in enumerate(admits):
            emitted += self._emit(slot, int(first[i]))
        for bi, (g, grows, csrc, cdst) in enumerate(beams):
            emitted += self._beam_init(g, grows, csrc, cdst, logits[n + bi])
        return emitted

    # ------------------------------------------------------------ beams
    def _group_live_blocks(self, g: _BeamGroup) -> int:
        """Distinct pool blocks held by the whole group (shared prompt
        blocks appear in several beams' tables — count them once)."""
        return len({b for sid in g.sid.values()
                    for b in self.mgr.tables.get(sid, []) if b is not None})

    def _update_resv_group(self, rid: int):
        self.kv.update(rid, live=self._group_live_blocks(self.groups[rid]))

    def _new_sid(self, rid):
        self._sid_counter += 1
        return (rid, self._sid_counter)

    def _beam_alloc(self, slots, req: Request):
        """Host/manager phase of beam admission: allocate the prompt under
        beam 0's key and fork the other beams copy-on-write. Returns the
        group plus the fork data; the prompt itself rides as ONE row of
        the shared admission prefill."""
        k, s, rid = req.num_beams, len(req.prompt), req.req_id
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        g = _BeamGroup(req=req, slots=list(slots), s=s)
        g.sid = {j: self._new_sid(rid) for j in range(k)}
        # protect same-tick greedy admits: their prefill rows are staged
        # but the scatter hasn't run yet (this is called mid-_prefill)
        prot = self._staged_admits
        self._mgr_retry(self.mgr.allocate, g.sid[0], s, protect=prot)
        rows = np.full((k, max_b), nb, np.int32)
        copy_src = np.full(k, nb, np.int32)
        copy_dst = np.full(k, nb, np.int32)
        for j in range(1, k):
            pair = self._mgr_retry(self.mgr.fork, g.sid[0], g.sid[j], s,
                                   protect=prot)
            if pair is not None:
                copy_src[j], copy_dst[j] = pair
        for j in range(k):
            t = self.mgr.tables[g.sid[j]]
            rows[j, :len(t)] = t
        return g, rows, copy_src, copy_dst

    def _beam_init(self, g: _BeamGroup, rows, copy_src, copy_dst,
                   logits_row):
        """Device-state phase after the shared prefill: install the forked
        tables, init the selection state from the prompt's last logits,
        then run the group's FIRST select so its slots enter this tick's
        forward with real beam tokens."""
        req, s, rid, k = g.req, g.s, g.req.req_id, g.req.num_beams
        self.exe.beam_group_update(g.slots, rows, s, copy_src, copy_dst)
        neg = jnp.float32(-1e9)
        vocab = self.model.cfg.vocab_size
        logp0 = jax.nn.log_softmax(logits_row.astype(jnp.float32))
        g.logp = jnp.broadcast_to(logp0[None], (k, vocab))
        g.running_lp = jnp.asarray([0.0] + [float(neg)] * (k - 1),
                                   jnp.float32)
        max_len = s + req.max_new_tokens
        g.seqs = jnp.zeros((k, max_len), jnp.int32).at[:, :s].set(
            jnp.asarray(req.prompt)[None])
        g.fin_seqs = jnp.zeros_like(g.seqs)
        g.fin_scores = jnp.full((k,), neg, jnp.float32)

        for slot in g.slots:
            self.slot_req[slot] = rid
            self.active[slot] = True
            self.is_beam[slot] = True
            self.cur[slot] = s
            self.temps[slot] = 0.0       # beam tokens come from select
            self.top_ps[slot] = 1.0
        self.groups[rid] = g
        self._update_resv_group(rid)
        return self._beam_advance(rid, g)

    def _beam_advance(self, rid: int, g: _BeamGroup):
        """One beam select over the group's pending logp; fork the caches
        along the chosen parents (or finalize at the last select).
        Selection/fork math mirrors ``paged_beam_search`` exactly."""
        k = g.req.num_beams
        (g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, new_beam,
         new_tok) = _BEAM_SELECT_JIT(
            g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, g.logp,
            jnp.int32(g.i), g.s, self.eos_token_id,
            float(g.req.length_penalty))
        if g.i == g.req.max_new_tokens - 1:
            return self._finalize_beam(rid, g)
        parents = np.asarray(new_beam)
        toks = np.asarray(new_tok)
        cur = g.s + g.i                       # tokens stored per beam
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        rows = np.full((k, max_b), nb, np.int32)
        copy_src = np.full(k, nb, np.int32)
        copy_dst = np.full(k, nb, np.int32)
        new_sids = {}
        for j in range(k):
            dst = self._new_sid(rid)
            pair = self._mgr_retry(self.mgr.fork,
                                   g.sid[int(parents[j])], dst, cur)
            if pair is not None:
                copy_src[j], copy_dst[j] = pair
            new_sids[j] = dst
        for j in range(k):
            self.mgr.free(g.sid[j])
        g.sid = new_sids
        for j in range(k):
            t = self._mgr_retry(                      # room for the write
                self.mgr.allocate, g.sid[j], cur + 1)
            rows[j, :len(t)] = t
        self.exe.beam_group_update(g.slots, rows, cur, copy_src, copy_dst)
        self._update_resv_group(rid)
        for j, slot in enumerate(g.slots):
            self.last_tok[slot] = toks[j]
        g.i += 1
        return []

    def _finalize_beam(self, rid: int, g: _BeamGroup):
        req = g.req
        best_seq, best_score = _beam_finalize(
            g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, g.s,
            req.max_new_tokens, self.eos_token_id,
            float(req.length_penalty))
        req.tokens = [int(t) for t in np.asarray(best_seq)[g.s:]]
        req.beam_score = float(best_score)
        req.done = True
        req.finish_reason = "beam"
        _FINISHED.inc(reason="beam")
        if req.tenant_id is not None:
            _TENANT_FINISHED.inc(tenant=tenant_label(req.tenant_id),
                                 reason="beam")
        _TOKENS.inc(len(req.tokens))
        GOODPUT.good(len(req.tokens), tenant=req.tenant_id)
        REQUESTS.tokens(req, len(req.tokens))
        REQUESTS.event(req, "kv_peak", replica=self.trace_name,
                       blocks=sum(self.kv.take_peak(s)
                                  for s in g.sid.values()))
        REQUESTS.finish(req, "beam", replica=self.trace_name)
        for sid in g.sid.values():
            self.mgr.free(sid)
        for slot in g.slots:
            self.active[slot] = False
            self.is_beam[slot] = False
            self.slot_req[slot] = -1
        self.kv.release(rid)
        del self.groups[rid]
        return [(rid, t) for t in req.tokens]

    def _prefill_chunks(self):
        """One chunk (≤ max_prompt_len tokens) for every in-flight
        chunked prefill — vLLM-style: long prompts stream in across
        ticks while other slots keep decoding. The final chunk samples
        the request's first token and activates its slot."""
        self._apply_prefix_copies()
        if not self.prefilling:
            return []
        a_cap = self.num_slots
        cap = self.max_prompt_len
        # ladder L2: shrink the per-tick chunk budget, not the jitted
        # geometry — the ids array keeps its (a_cap, cap) shape (lens
        # just come up shorter), so degrading never recompiles
        budget = (cap if self.degrade is None
                  else min(cap, self.degrade.prefill_budget(cap)))
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        ids = np.zeros((a_cap, cap), np.int32)
        lens = np.zeros(a_cap, np.int32)
        offs = np.zeros(a_cap, np.int32)
        slots = np.full(a_cap, self.num_slots, np.int32)
        rows = np.full((a_cap, max_b), nb, np.int32)
        batch = list(self.prefilling.items())[:a_cap]
        row_aidx = np.full(a_cap, -1, np.int64)
        progressed = False
        staged = set()       # rows already in the jitted batch: their KV
        for i, (rid, (slot, consumed)) in enumerate(batch):
            if rid not in self.prefilling:   # scatter is pending — a later
                continue     # row's preemption must never evict them
            req = self.requests[rid]
            chunk = self._pr(req)[consumed: consumed + budget]
            t = self._allocate_or_preempt(rid, consumed + len(chunk),
                                          protect=staged)
            if t is None:
                continue         # no blocks this tick: row stays queued
            progressed = True
            staged.add(rid)
            self._update_resv(rid)
            REQUESTS.event(req, "prefill_chunk", replica=self.trace_name,
                           slot=slot, offset=consumed, tokens=len(chunk))
            ids[i, :len(chunk)] = chunk
            lens[i] = len(chunk)
            offs[i] = consumed
            slots[i] = slot
            rows[i, :len(t)] = t
            row_aidx[i] = self._req_aidx(req)
        if (not progressed and not self.active.any() and not self.groups):
            # nothing decoded this tick and no prefill row got blocks even
            # though preemption could evict every OTHER prefill: the pool
            # cannot fit one chunk of the sole remaining request — no
            # future tick can differ, so raise instead of spinning
            FLIGHT.record("serving.alloc_fail",
                          rids=[int(r) for r in self.prefilling],
                          **self.kv.ledger.flight_fields())
            FLIGHT.dump(reason="kv_alloc_fail")
            raise MemoryError(
                "paged pool cannot fit one prefill chunk of the remaining "
                "request(s) even after preemption — increase num_blocks or "
                "reduce max_prompt_len (chunk size)")
        if not progressed:
            # every prefilling row is starved of blocks this tick (decode
            # keeps the engine alive): the batch is all-sentinel, so the
            # padded chunk forward would scatter nothing — skip it
            return []
        logits = self.exe.prefill_chunk(ids, lens, offs, slots, rows,
                                        lora=self._lora_arg(row_aidx, cap))
        # padded sentinel rows burned device FLOPs on no request's behalf
        GOODPUT.waste("pad_rows", (a_cap - len(staged)) * cap)
        # roofline: one weight pass; each chunk attends its own tokens
        # plus everything already consumed (its offset)
        self._acc_phase("prefill", int(lens.sum()), 1,
                        self._ctx_causal(lens, offs))
        emitted = []
        done_rows = []
        for i, (rid, (slot, consumed)) in enumerate(batch):
            if rid not in self.prefilling:
                continue     # evicted mid-batch: must not re-add its row
            req = self.requests[rid]
            consumed += int(lens[i])
            if consumed < len(self._pr(req)):
                self.prefilling[rid] = (slot, consumed)
                continue
            done_rows.append((i, rid, slot))
        if done_rows:
            row_t = np.zeros(a_cap, np.float32)
            row_p = np.ones(a_cap, np.float32)
            for i, rid, slot in done_rows:
                req = self.requests[rid]
                row_t[i] = (self.default_temp if req.temperature is None
                            else req.temperature)
                row_p[i] = (self.default_top_p if req.top_p is None
                            else req.top_p)
                # bind grammar BEFORE the first-token sample so the mask
                # bias covers it (state replays req.tokens for resumes)
                self._bind_grammar(slot, req)
            first = self.exe.sample(
                logits, row_t, row_p,
                bias=self._grammar_bias_rows(
                    [(i, s) for i, _, s in done_rows], a_cap))
            for i, rid, slot in done_rows:
                req = self.requests[rid]
                del self.prefilling[rid]
                p = self._pr(req)
                if self.prefix_caching:
                    self.mgr.commit_prefix(rid, p,
                                           adapter=req.adapter_id)
                t = self.mgr.tables[rid]
                self.active[slot] = True
                self.cur[slot] = len(p)
                self.gen[slot] = 0
                self.max_gen[slot] = self._remaining(req)
                self._adm_counter += 1
                self.adm_order[slot] = self._adm_counter
                self.table_len[slot] = len(t)
                self.temps[slot] = row_t[i]
                self.top_ps[slot] = row_p[i]
                self.slot_aidx[slot] = self._req_aidx(req)
                # cached/long prompts land here — the site where a radix
                # adoption can seed the draft frontier from resident K/V
                self._seed_draft(slot, req)
                self.slot_k[slot] = self.spec_k
                self._acc_ema[slot] = 1.0
                emitted += self._emit(slot, int(first[i]))
        return emitted

    def _apply_prefix_copies(self):
        """Drain the radix manager's host-side COW plan (partial boundary
        blocks adopted at admission) into ONE device copy. Runs before
        any other program of the tick writes the pool, so jax data
        dependencies order the copy ahead of the adopters' prefill
        chunks and ahead of any reallocation of a source block."""
        take = getattr(self.mgr, "take_copy_plan", None)
        if take is None:
            return
        pairs = take()
        if pairs:
            self.exe.apply_block_copies(pairs)

    # --------------------------------------------------------- preemption
    def _preempt(self, protect_rid=None) -> bool:
        # preemption rewrites a victim's resume prompt from req.tokens —
        # tokens still in flight in the async window must land first or
        # the replayed stream would silently drop them
        self._drain_async("boundary")
        return self.sched.preempt(self, protect_rid)

    _protect = staticmethod(Scheduler._protect)

    def _preempt_prefilling(self, protect_rid=None) -> bool:
        self._drain_async("boundary")
        return self.sched.preempt_prefilling(self, protect_rid)

    def _preempt_from(self, cand) -> bool:
        self._drain_async("boundary")
        return self.sched.preempt_from(self, cand)

    def _allocate_or_preempt(self, rid: int, n_tokens: int, protect=None):
        """mgr.allocate with out-of-blocks recovery: preempt greedy slots
        (never ``rid`` itself, nor anything in ``protect`` — rows already
        staged into this tick's jitted batch) until the allocation fits.
        Returns the table, or None when preemption is off / nothing could
        be freed (caller skips this row for the tick — progress resumes
        when blocks free up).

        Respects OTHER requests' standing reservations: a greedy request
        (which carries none under preemption) must preempt before dipping
        into blocks a beam group's worst-case reservation counts on —
        otherwise a later beam select can raise MemoryError out of
        ``step()`` mid-update, corrupting engine state."""
        protect = self._protect(protect) | {rid}
        while True:
            others = self._reserved - self._resv.get(rid, 0)
            # need mirrors mgr.allocate: table POSITIONS — including the
            # None placeholders window recycling leaves — already cover
            # their token span; counting only live blocks would inflate
            # need without bound as a windowed sequence recycles
            # (spurious preemption storm, then a crash)
            need = (self.mgr.blocks_needed(n_tokens)
                    - len(self.mgr.tables.get(rid, [])))
            try:
                # chaos hook: an injected MemoryError exercises the same
                # preempt-and-retry recovery a genuinely dry pool would
                fault_point("serving.alloc", rid=rid, engine=self)
                if need > self.mgr.free_blocks - max(0, others):
                    raise MemoryError("allocation would dip into blocks "
                                      "reserved by other requests")
                return self.mgr.allocate(rid, n_tokens)
            except MemoryError:
                if not self.preemption or not self._preempt(
                        protect_rid=protect):
                    if self.preemption:
                        return None
                    # hard failure escapes step(): leave the ledger's view
                    # of who holds the missing blocks in the flight ring
                    FLIGHT.record("serving.alloc_fail", rid=int(rid),
                                  **self.kv.ledger.flight_fields())
                    raise

    def _mgr_retry(self, fn, *a, protect=None):
        """Beam-group block growth with out-of-blocks recovery: route
        through greedy preemption instead of letting MemoryError escape
        ``step()`` mid-cache-update. The group's worst-case reservation
        (+2 transient fork blocks per beam) should make this unreachable
        now that greedy growth respects reservations; this is the
        belt-and-braces path. ``protect``: req_ids whose prefill rows are
        staged but not yet scattered (evicting one would corrupt the KV
        writes about to land)."""
        while True:
            try:
                return fn(*a)
            except MemoryError:
                if not self.preemption or not self._preempt(
                        protect_rid=protect):
                    raise

    # ------------------------------------------------- speculative decode
    def _spec_probs(self, logits_row, temp, top_p):
        """Host mirror of ``decoding._sample_rows``'s filtered target
        distribution for one row (temperature > 0): temperature scale →
        static top_k cut → nucleus (top_p) cut → renormalise. The accept
        rule must compare proposals against EXACTLY the distribution the
        non-spec tick samples from, or speculation would change the
        output law."""
        scaled = np.asarray(logits_row, np.float64) / temp
        if self.top_k is not None and self.top_k > 0:
            kth = np.sort(scaled)[-self.top_k]
            scaled = np.where(scaled < kth, -1e30, scaled)
        srt = np.sort(scaled)[::-1]
        e = np.exp(srt - srt[0])
        cum = np.cumsum(e / e.sum())
        cutoff = srt[int((cum < top_p).sum())]
        scaled = np.where(scaled < cutoff, -1e30, scaled)
        e = np.exp(scaled - scaled.max())
        return e / e.sum()

    def _committed_seq(self, slot: int) -> np.ndarray:
        """The slot's committed sequence: effective prompt + tokens
        generated SINCE activation (earlier generations are already baked
        into the resume prompt). Its last token is ``last_tok`` — sampled
        but not yet written to the target cache — so len == cur + 1."""
        req = self.requests[int(self.slot_req[slot])]
        g = int(self.gen[slot])
        toks = np.asarray(req.tokens[len(req.tokens) - g:], np.int32)
        return np.concatenate([self._pr(req), toks])

    def _seed_draft(self, slot: int, req):
        """Seed a freshly activated slot's draft frontier from the
        resident draft cache (ISSUE 11, closing PR 9's REMAINING). The
        dense draft cache is per-slot and nothing writes it while the
        slot is parked, so rows 0..len(resident)-1 still hold the draft
        K/V of the previous session's committed prefix. When the new
        request radix-adopted a prefix that matches those resident ids,
        the adopted span's draft-side re-prefill is pure replay — skip
        it by advancing ``draft_cur`` past the match. The reuse window
        is capped at the adopted span: only radix-adopted tokens were
        ever drafted before, and the accept rule preserves the target
        law for ANY draft state, so a conservative cap costs nothing in
        correctness. ``PT_DRAFT_REUSE=0`` kills the seeding (fresh
        re-feed, exactly the old behaviour)."""
        p = self._pr(req)
        adopted = int(getattr(req, "_adopted", 0))
        self._adopted_span[slot] = min(adopted, len(p))
        reuse = 0
        if (adopted > 0 and self.exe.draft_model is not None
                and os.environ.get("PT_DRAFT_REUSE", "1") != "0"):
            res = self._draft_resident.get(slot)
            if res is not None and len(res):
                # cap below len(p): the steady feed needs >= 1 pending
                # token so its last logit can seed the first proposal
                m = min(len(res), adopted, len(p) - 1)
                if m > 0:
                    eq = np.asarray(res[:m]) == np.asarray(p[:m])
                    reuse = int(m if eq.all() else np.argmin(eq))
        self.draft_cur[slot] = reuse
        if reuse:
            GOODPUT.saved(reuse, tenant=req.tenant_id)
            _SPEC_DRAFT_REUSE.inc(reuse)

    def _spec_draft(self, staged, seqs):
        """Draft phase: catch each staged slot's draft cache up to its
        committed frontier (chunked, for freshly admitted/replayed slots
        whose draft cache is empty), then autoregressively propose up to
        k_eff tokens per slot. Returns (props, qs) keyed by slot; qs[slot]
        is None for greedy rows, else the per-proposal draft
        distributions the accept rule needs."""
        ns = self.num_slots
        kmax = max(k for _, _, k in staged)
        Cs = self.spec_k + 1

        # ---- catch-up: wide chunks until every pending suffix fits the
        # steady feed (pending >= 1 always — last_tok is never in cache)
        CH = max(self.max_prompt_len, Cs)
        while True:
            pend_len = {s: len(seqs[s]) - int(self.draft_cur[s])
                        for s, _, _ in staged}
            if max(pend_len.values()) <= Cs:
                break
            ids = np.zeros((ns, CH), np.int32)
            cl = np.zeros(ns, np.int32)
            rp = np.zeros(ns, np.int32)
            for s, rid, _ in staged:
                if pend_len[s] <= Cs:
                    continue               # already caught up: no writes
                n = min(pend_len[s] - 1, CH)   # keep >= 1 for the steady feed
                dc = int(self.draft_cur[s])
                ids[s, :n] = seqs[s][dc: dc + n]
                cl[s] = n
                rp[s] = dc
                # re-embedding inside the radix-adopted span is pure
                # replay (first-time prompt embedding is not waste)
                GOODPUT.waste("replay_prefill",
                              min(dc + n, int(self._adopted_span[s])) - dc,
                              tenant=getattr(self.requests.get(rid),
                                             "tenant_id", None))
            self.exe.draft_rows(ids, rp, cl)
            self._acc_phase("spec_draft", int(cl.sum()), 1,
                            self._ctx_causal(cl, rp))
            for s, _, _ in staged:
                self.draft_cur[s] += int(cl[s])

        # ---- steady feed: the pending suffix (<= k+1 tokens) in one
        # fixed-width chunk; its last logit seeds the first proposal
        ids = np.zeros((ns, Cs), np.int32)
        cl = np.zeros(ns, np.int32)
        rp = np.zeros(ns, np.int32)
        for s, rid, _ in staged:
            dc = int(self.draft_cur[s])
            pend = seqs[s][dc:]
            ids[s, :len(pend)] = pend
            cl[s] = len(pend)
            rp[s] = dc
            GOODPUT.waste("replay_prefill",
                          min(dc + len(pend),
                              int(self._adopted_span[s])) - dc,
                          tenant=getattr(self.requests.get(rid),
                                         "tenant_id", None))
        dl = self.exe.draft_rows(ids, rp, cl)
        self._acc_phase("spec_draft", int(cl.sum()), 1,
                        self._ctx_causal(cl, rp))
        for s, _, _ in staged:
            self.draft_cur[s] += int(cl[s])      # == cur + 1 now
        dlast = jnp.take_along_axis(
            dl, jnp.maximum(jnp.asarray(cl, jnp.int32) - 1,
                            0)[:, None, None], axis=1)[:, 0]

        props = {s: [] for s, _, _ in staged}
        qs = {s: (None if float(self.temps[s]) == 0.0 else [])
              for s, _, _ in staged}

        def pick(slot, row):
            temp = float(self.temps[slot])
            if temp == 0.0:
                return int(np.argmax(row))
            z = np.asarray(row, np.float64) / temp
            e = np.exp(z - z.max())
            q = e / e.sum()
            qs[slot].append(q)
            return int(self._spec_rs.choice(q.size, p=q))

        def pick_all(logits_2d, rows_feeding):
            ng = [s for s in rows_feeding if float(self.temps[s]) != 0.0]
            greedy = [s for s in rows_feeding
                      if float(self.temps[s]) == 0.0]
            if greedy:           # fetch [ns] ints, never the [ns, V] block
                am = np.asarray(jnp.argmax(
                    logits_2d.astype(jnp.float32), axis=-1))
                self._spec_fetch_bytes += am.nbytes
                for s in greedy:
                    props[s].append(int(am[s]))
            if ng:
                # gather ONLY the non-greedy rows on device before the
                # host fetch — one temperature slot no longer taxes every
                # greedy slot's D2H with the full [ns, V] block
                sub = np.asarray(
                    logits_2d[jnp.asarray(ng)].astype(jnp.float32))
                self._spec_fetch_bytes += sub.nbytes
                for i, s in enumerate(ng):
                    props[s].append(pick(s, sub[i]))

        pick_all(dlast, [s for s, _, _ in staged])
        # ---- autoregressive proposal rounds (single-token feeds)
        for r in range(1, kmax):
            feeding = [s for s, _, k in staged if k > r]
            if not feeding:
                break
            ids1 = np.zeros((ns, 1), np.int32)
            cl1 = np.zeros(ns, np.int32)
            rp1 = np.zeros(ns, np.int32)
            for s in feeding:
                ids1[s, 0] = props[s][-1]
                cl1[s] = 1
                rp1[s] = int(self.draft_cur[s])
            dl1 = self.exe.draft_rows(ids1, rp1, cl1)
            self._acc_phase("spec_draft", int(cl1.sum()), 1,
                            self._ctx_causal(cl1, rp1))
            for s in feeding:
                self.draft_cur[s] += 1           # == cur + r + 1
            pick_all(dl1[:, 0], feeding)
        return props, qs

    def _spec_tick(self, elig):
        """One draft-and-verify round for the eligible slots. Returns
        (handled mask, emitted): handled slots advanced up to k_eff+1
        tokens and skip this tick's one-token path.

        Staging allocates verify coverage (cur + k_eff + 1 tokens) per
        slot BEFORE any device work, protecting already-staged rows from
        preemption — mirrors ``_prefill_chunks``. The ``serving.spec_verify``
        fault point fires before the donating verify jit, so an injected
        exception aborts with the cache, tables, and ledgers exactly as
        the staging left them (staged blocks live in request tables — the
        normal free path reclaims them) and the tick falls back to
        one-token decode for every slot."""
        handled = np.zeros(self.num_slots, bool)
        emitted: list = []
        ns = self.num_slots
        # ---- stage: clamp k, allocate coverage for the worst case ----
        staged = []                        # (slot, rid, k_eff)
        staged_rids: set = set()
        for slot in np.nonzero(elig)[0]:
            slot = int(slot)
            if not self.active[slot]:
                continue                   # evicted by an earlier staging
            rid = int(self.slot_req[slot])
            k_cap = int(self.slot_k[slot]) if self.spec_adaptive \
                else self.spec_k
            k_eff = min(k_cap, int(self.max_gen[slot] - self.gen[slot]) - 1)
            if k_eff < 1:
                continue
            t = self._allocate_or_preempt(
                rid, int(self.cur[slot]) + k_eff + 1, protect=staged_rids)
            if t is None:
                continue                   # dry pool: one-token path today
            self._update_resv(rid)
            self.table_len[slot] = len(t)
            staged.append((slot, rid, k_eff))
            staged_rids.add(rid)
        staged = [(s, r, k) for s, r, k in staged if self.active[s]]
        if not staged:
            return handled, emitted

        seqs = {s: self._committed_seq(s) for s, _, _ in staged}
        with self._tick_timer("draft"), \
                _span("serving.draft", slots=len(staged)):
            props, qs = self._spec_draft(staged, seqs)

        # ---- verify: ONE batched target chunk over (slots, k_eff+1) ----
        C = self.spec_k + 1
        ids = np.zeros((ns, C), np.int32)
        clens = np.zeros(ns, np.int32)
        offs = np.zeros(ns, np.int32)
        slot_ids = np.full(ns, ns, np.int32)
        rows = np.full((ns, self.max_blocks_per_seq), self.mgr.num_blocks,
                       np.int32)
        v_aidx = np.full(ns, -1, np.int64)
        for slot, rid, k_eff in staged:
            ids[slot, 0] = self.last_tok[slot]
            ids[slot, 1: 1 + k_eff] = props[slot][:k_eff]
            clens[slot] = k_eff + 1
            offs[slot] = self.cur[slot]
            slot_ids[slot] = slot
            t = self.mgr.tables[rid]
            rows[slot, :len(t)] = t
            v_aidx[slot] = self.slot_aidx[slot]
        try:
            # chaos hook BEFORE the donating jit: an exception here must
            # leave self.cache intact (exception atomicity) — after the
            # donation there is no cache to fall back to
            fault_point("serving.spec_verify", engine=self,
                        slots=[s for s, _, _ in staged])
        except Exception as e:
            self.stats["spec_fallbacks"] += 1
            _SPEC_FALLBACKS.inc()
            FLIGHT.record("serving.spec_fallback",
                          error=f"{type(e).__name__}: {e}")
            # every drafted token of this round was burned unverified
            # (charged per slot so the metering ledger bills the tenant
            # whose draft burned, not __system__)
            for _, rid, k_eff in staged:
                GOODPUT.waste("chaos_abort", k_eff,
                              tenant=getattr(self.requests.get(rid),
                                             "tenant_id", None))
            # draft frontiers ran ahead of the commit that never came;
            # roll them back so the next round re-feeds from the frontier
            for slot, _, _ in staged:
                self.draft_cur[slot] = min(int(self.draft_cur[slot]),
                                           int(self.cur[slot]) + 1)
                # the rolled-back frontier still covers the committed
                # prefix: keep the resident snapshot coherent for reuse
                self._draft_resident[slot] = np.asarray(
                    seqs[slot][:int(self.draft_cur[slot])], np.int32)
                # staging extended the HOST table, but only the verify jit
                # would have installed those entries in the DEVICE row —
                # roll table_len back to what the device actually covers
                # so _grow_tables re-emits the missing entries; a later
                # spec round is self-healing (verify gets the full row)
                self.table_len[slot] = -(-int(self.cur[slot])
                                         // self.block_size)
            return np.zeros(self.num_slots, bool), []
        t_dev = time.perf_counter()
        with self._tick_timer("verify"), \
                _span("serving.verify", slots=len(staged)):
            logits = np.asarray(self.exe.verify_chunk(
                ids, clens, offs, slot_ids, rows,
                lora=self._lora_arg(v_aidx, C)).astype(jnp.float32))
        self.stats["device_s"] += time.perf_counter() - t_dev
        # whole sentinel rows of the fixed-shape verify batch are waste
        GOODPUT.waste("pad_rows", (ns - len(staged)) * C)
        # roofline: one target weight pass; each verify row attends its
        # k_eff+1 chunk tokens plus the committed context at its offset
        self._acc_phase("spec_verify", int(clens.sum()), 1,
                        self._ctx_causal(clens, offs))

        # ---- accept/commit per slot; ONE batched length rewind after ----
        rw_slots = np.full(ns, ns, np.int32)
        rw_lens = np.zeros(ns, np.int32)
        for slot, rid, k_eff in staged:
            temp = float(self.temps[slot])
            row = logits[slot]                        # [C, V]
            # grammar slots: reject mask-violating drafts BEFORE the
            # accept law ever sees them (k_use truncates at the first
            # illegal proposal), then bias each verify position with the
            # mask of the state reached by accepting the proposals ahead
            # of it — the accept rule compares against EXACTLY the
            # masked distribution the non-spec tick samples from, so
            # speculation cannot change the constrained output law
            g = self._grammar.get(slot)
            gb, k_use = None, k_eff
            if g is not None:
                aut, st = g[0], g[1]
                gb, k_use = [], 0
                for i in range(k_eff):
                    b = aut.bias(st)
                    gb.append(b)
                    t_i = int(props[slot][i])
                    if b[t_i] != 0.0:
                        _GRAMMAR_SPEC_REJECTS.inc(k_eff - i)
                        break
                    st = aut.advance(st, t_i)
                    k_use += 1
                if k_use == k_eff:
                    gb.append(aut.bias(st))   # the bonus position's mask
            if temp == 0.0:
                vrow = (row[: k_use + 1] if gb is None
                        else row[: k_use + 1] + np.asarray(gb, np.float32))
                vs = vrow.argmax(axis=-1)
                n_acc = int(greedy_accept_length(vs[:k_use],
                                                 props[slot][:k_use]))
                new = [int(x) for x in props[slot][:n_acc]] \
                    + [int(vs[n_acc])]
            else:
                ps = [self._spec_probs(
                          row[i] if gb is None else row[i] + gb[i],
                          temp, float(self.top_ps[slot]))
                      for i in range(k_use + 1)]
                new, n_acc = stochastic_accept_row(
                    props[slot][:k_use], qs[slot], ps, self._spec_rs)
            cur0 = int(self.cur[slot])
            cur1 = cur0 + n_acc + 1
            self.cur[slot] = cur1
            rw_slots[slot] = slot
            rw_lens[slot] = cur1
            # draft frontier rolls back past rejected positions (stale
            # entries are overwritten by the next round's feed)
            self.draft_cur[slot] = min(int(self.draft_cur[slot]), cur1)
            # snapshot the token ids the draft cache now holds at
            # 0..draft_cur-1 — the reuse seed for this slot's NEXT
            # session (rows 0..draft_cur-1 always hold the committed
            # prefix after the rollback above)
            self._draft_resident[slot] = np.asarray(
                np.concatenate([seqs[slot], np.asarray(new, np.int32)])
                [:int(self.draft_cur[slot])], np.int32)
            if self.spec_adaptive:
                self._acc_ema[slot] = (0.5 * self._acc_ema[slot]
                                       + 0.5 * (n_acc / k_eff))
                self.slot_k[slot] = int(np.clip(
                    round(self._acc_ema[slot] * self.spec_k), 1,
                    self.spec_k))
            self.stats["spec_proposed"] += k_eff
            self.stats["spec_accepted"] += n_acc
            _SPEC_PROPOSED.inc(k_eff)
            _SPEC_ACCEPTED.inc(n_acc)
            _SPEC_TOKENS.observe(len(new))
            GOODPUT.waste("spec_rejected", k_eff - n_acc,
                          tenant=getattr(self.requests.get(rid),
                                         "tenant_id", None))
            REQUESTS.spec(self.requests.get(rid), k_eff, n_acc)
            handled[slot] = True
            for tok in new:
                emitted += self._emit(slot, int(tok))
                if self.slot_req[slot] < 0:
                    break      # EOS/length finished the request mid-list:
                    #            the rest of the accepted tokens is moot
        if self.stats["spec_proposed"]:
            _SPEC_RATE.set(self.stats["spec_accepted"]
                           / self.stats["spec_proposed"])
        # one rewind for all staged rows: length pointers only — verify
        # wrote k_eff+1 positions, the commit kept n_acc+1 of them
        self.exe.rewind_lens(rw_slots, rw_lens)
        self.stats["spec_ticks"] += 1
        return handled, emitted

    # ------------------------------------------------------------- decode
    def _grow_tables(self, mask=None):
        """At most one new block per slot per tick; returns the incremental
        (rows, cols, vals) update triple (sentinel-padded, fixed shape).
        ``mask`` restricts growth to those slots (spec-handled slots skip
        the normal tick, so their updates must not ride a tick that may
        never run — their tables grow in the verify staging instead)."""
        rows = np.full(self.num_slots, self.num_slots, np.int32)
        cols = np.zeros(self.num_slots, np.int32)
        vals = np.zeros(self.num_slots, np.int32)
        base = (self.active & ~self.is_beam) if mask is None else mask
        crossing = base & (
            self.cur // self.block_size >= self.table_len)
        for slot in np.nonzero(crossing)[0]:     # ≤ once per bs ticks/slot
            if not self.active[slot]:
                continue                 # preempted earlier in this loop
            rid = int(self.slot_req[slot])
            t = self._allocate_or_preempt(rid, int(self.cur[slot]) + 1)
            if t is None:
                # nothing else to evict: preempt THIS slot (it re-queues
                # with its progress and resumes when blocks free up)
                if not self._preempt_from([int(slot)]):
                    raise MemoryError(
                        "paged cache out of blocks and the growing slot "
                        "is not preemptible (windowed/dynamic-rope resume "
                        "exceeds max_prompt_len)")
                continue
            self._update_resv(rid)
            # install the next entry the DEVICE row is missing — normally
            # the block just allocated (table_len == len(t)-1), but after
            # a spec-verify fallback the host table can be ahead by more
            # than one staged-but-never-installed block
            idx = min(int(self.table_len[slot]), len(t) - 1)
            rows[slot] = slot
            cols[slot] = idx
            vals[slot] = t[idx]
            self.table_len[slot] = idx + 1
        if self.window is not None:
            self._recycle_window(np.nonzero(self.active & ~self.is_beam)[0])
        return rows, cols, vals

    def _emit(self, slot: int, token: int):
        """Record one sampled token for the request in ``slot``; finish on
        EOS or length. Returns [(req_id, token)]."""
        rid = int(self.slot_req[slot])
        if rid < 0:
            return []        # slot emptied mid-tick (stream-side cancel)
        req = self.requests[rid]
        req.tokens.append(token)
        _TOKENS.inc()
        GOODPUT.good(1, tenant=req.tenant_id)
        if req.tenant_id is not None:
            _TENANT_TOKENS.inc(tenant=tenant_label(req.tenant_id))
        g = self._grammar.get(slot)
        if g is not None:
            # advance the mask state past the committed token (EOS keeps
            # the state; an illegal token here would be a sampler bug and
            # raises loudly rather than derail the automaton silently)
            g[1] = g[0].advance(g[1], token)
            _GRAMMAR_TOKENS.inc()
        now = self._clock()
        if req._first_tok_t is None:
            req._first_tok_t = now
            if req._submit_t is not None:
                _TTFT.observe(max(0.0, now - req._submit_t))
                if req.tenant_id is not None:
                    _TENANT_TTFT.observe(
                        max(0.0, now - req._submit_t),
                        tenant=tenant_label(req.tenant_id))
            REQUESTS.event(req, "first_token", replica=self.trace_name,
                           slot=slot)
        elif req._last_tok_t is not None:
            _TOK_LAT.observe(max(0.0, now - req._last_tok_t))
            if req.tenant_id is not None:
                _TENANT_TOK_LAT.observe(
                    max(0.0, now - req._last_tok_t),
                    tenant=tenant_label(req.tenant_id))
        req._last_tok_t = now
        if req.stream is not None:
            req.stream(req, token)
        self.last_tok[slot] = token
        self.gen[slot] += 1
        REQUESTS.tokens(req)
        eos = self.eos_token_id is not None and token == self.eos_token_id
        if eos or self.gen[slot] >= self.max_gen[slot]:
            req.done = True
            req.finish_reason = "eos" if eos else "length"
            self._gauge_force = True     # finish boundary: exact sweep
            _FINISHED.inc(reason=req.finish_reason)
            if req.tenant_id is not None:
                _TENANT_FINISHED.inc(tenant=tenant_label(req.tenant_id),
                                     reason=req.finish_reason)
            if self.prefix_caching:
                # commit the GENERATED span too before the blocks park —
                # decode output becomes matchable (multi-turn chat
                # re-submits prompt+answer as the next prompt). Commit
                # only up to the cache frontier ``cur``: the token just
                # sampled has no KV scattered yet
                seq = np.concatenate([req.prompt,
                                      np.asarray(req.tokens, np.int32)])
                self.mgr.commit_prefix(
                    rid, seq[:min(len(seq), int(self.cur[slot]))],
                    adapter=req.adapter_id)
            self.mgr.free(rid)
            self.kv.release(rid)
            self.active[slot] = False
            self.slot_req[slot] = -1
            self.slot_aidx[slot] = -1
            self._grammar.pop(slot, None)
            self._release_adapter(rid)
            REQUESTS.event(req, "kv_peak", replica=self.trace_name,
                           blocks=self.kv.take_peak(rid))
            REQUESTS.finish(req, req.finish_reason,
                            replica=self.trace_name)
        return [(rid, token)]

    # -------------------------------------------- KV handoff (ISSUE 7)
    def extract_sequence(self, rid: int) -> KVPayload:
        """Lift a prefilled/decoding greedy sequence OUT of this engine:
        gather its KV blocks into a dense payload, then free the slot,
        blocks, and ledger entry. The request leaves with its tokens; the
        payload carries everything a decode replica needs to continue
        bit-exactly (``install_sequence``). Raises for beam/chunk-mid
        requests — only ACTIVE greedy slots are extractable (the router
        extracts after the final prefill chunk activates the slot)."""
        self._drain_async("boundary")
        if self.cp > 1:
            raise NotImplementedError(
                "KV handoff under context parallelism (cp>1) is not "
                "supported — the gather program reads a single-device "
                "pool; ship from/to cp=1 replicas")
        slots = np.nonzero(self.slot_req == rid)[0]
        if not len(slots) or rid in self.prefilling or rid in self.groups:
            raise ValueError(f"req {rid} holds no active greedy slot")
        slot = int(slots[0])
        if self.is_beam[slot] or not self.active[slot]:
            raise ValueError(f"req {rid} holds no active greedy slot")
        if rid in self._adapter_pins:
            raise NotImplementedError(
                "cannot extract a multi-LoRA sequence — its KV was "
                "written under the adapter, and the receiving replica "
                "holds no pin on it")
        t = self.mgr.tables[rid]
        if any(b is None for b in t):
            raise NotImplementedError(
                "cannot extract a window-recycled sequence (holes in the "
                "block table)")
        idx = np.zeros(self.max_blocks_per_seq, np.int32)
        idx[:len(t)] = t
        k, v = _GATHER_BLOCKS_JIT(self.cache.k_pools, self.cache.v_pools,
                                  jnp.asarray(idx))
        ks = vs = None
        if self.cache.k_scales:
            # int8 pool: the codes are meaningless without their scales —
            # gather the scale rows through the same program (distinct
            # compile entry; the trailing dims differ)
            ks, vs = _GATHER_BLOCKS_JIT(self.cache.k_scales,
                                        self.cache.v_scales,
                                        jnp.asarray(idx))
        payload = KVPayload(
            req=self.requests[rid], cur=int(self.cur[slot]),
            gen=int(self.gen[slot]), last_tok=int(self.last_tok[slot]),
            n_blocks=len(t), block_size=self.block_size, k=k, v=v,
            k_scale=ks, v_scale=vs)
        # wire contract: geometry + checksums recorded while the blocks
        # are known-good, so the router can reject a partial transfer
        payload.seal()
        # gather landed — now release host state (same order as cancel)
        REQUESTS.event(payload.req, "kv_extract", replica=self.trace_name,
                       blocks=len(t), cur=int(self.cur[slot]))
        self.mgr.free(rid)
        REQUESTS.event(payload.req, "kv_peak", replica=self.trace_name,
                       blocks=self.kv.take_peak(rid))
        self.kv.release(rid)
        self.active[slot] = False
        self.slot_req[slot] = -1
        self.draft_cur[slot] = 0
        self.slot_aidx[slot] = -1
        self._grammar.pop(slot, None)
        self.sched.release(rid)
        return payload

    def snapshot_session(self, rid: int):
        """Host-side durability capture (ISSUE 16): prompt + generated
        ids + sampler RNG + adapter/grammar refs for one in-flight
        request — everything a surviving replica needs to resume the
        session by replaying prefill. Token ids only, never KV blocks,
        so the capture is tick-cheap. Returns None for unknown/finished
        requests; the ``serving.snapshot`` chaos site fires pre-capture,
        so an injected fault skips this capture cleanly (the caller
        keeps its previous, staler snapshot)."""
        req = self.requests.get(rid)
        if req is None or req.done:
            return None
        # the snapshot's token list and rng must be mutually consistent:
        # land any in-flight async ticks before capturing either
        self._drain_async("boundary")
        if req.done:
            return None
        fault_point("serving.snapshot", engine=self, rid=rid)
        snap = SessionSnapshot(
            req_id=rid, prompt=req.prompt, tokens=tuple(req.tokens),
            session_id=req.session_id, tenant_id=req.tenant_id,
            adapter_id=req.adapter_id, grammar=req.grammar,
            rng=self.rng, gen=len(req.tokens),
            captured_t=self.sched.clock())
        _SNAPSHOTS.inc()
        return snap

    def install_sequence(self, payload: KVPayload) -> bool:
        """Adopt a sequence extracted from another replica: scatter its
        blocks into this pool, install the block-table row + length, and
        activate a slot mid-decode. Returns False (payload untouched, no
        state changed) when no slot or not enough blocks are free —
        the router retries later. Exception-atomic: host bookkeeping is
        undone if allocation fails; the donating scatter runs last."""
        self._drain_async("boundary")
        if self._draining:
            raise EngineDrainingError(
                "engine is draining — finishing in-flight requests, "
                "admitting nothing new")
        req = payload.req
        if self.cp > 1:
            raise NotImplementedError(
                "KV handoff under context parallelism (cp>1) is not "
                "supported — the install scatter writes a single-device "
                "pool; ship from/to cp=1 replicas")
        if req.adapter_id is not None:
            raise NotImplementedError(
                "multi-LoRA sequences do not ride the KV handoff (the "
                "payload's KV depends on adapter weights this engine "
                "has not pinned)")
        if payload.block_size != self.block_size:
            raise ValueError(f"block_size mismatch: payload "
                             f"{payload.block_size} != {self.block_size}")
        pool = self.cache.k_pools[0]
        if (payload.k.shape[0] != len(self.cache.k_pools)
                or payload.k.shape[2:] != pool.shape[1:]):
            raise ValueError("KV payload geometry does not match this "
                             "engine's pool (layers/heads/head_dim)")
        if (payload.k_scale is not None) != bool(self.cache.k_scales):
            raise ValueError("KV payload quantization does not match this "
                             "engine's pool — source and target replicas "
                             "must share kv_dtype")
        if payload.cur + self._remaining(req) > self.max_seq_len:
            raise ValueError("sequence + remaining tokens exceeds this "
                             "engine's max_seq_len")
        rid = req.req_id
        if rid in self.requests:
            raise ValueError(f"req_id {rid} already exists")
        free = np.nonzero(self.slot_req < 0)[0]
        wc = self.mgr.blocks_needed(payload.cur + self._remaining(req))
        if not len(free) or wc > self.mgr.free_blocks - self._reserved:
            return False
        slot = int(free[0])
        self.sched.adopt(req)
        self.kv.begin(rid, wc)
        try:
            t = self.mgr.allocate(rid, payload.cur)
        except MemoryError:
            self.kv.release(rid)
            self.sched.release(rid)
            return False
        self.kv.update(rid)
        # NOTE: the installed blocks are NOT committed to the prefix
        # cache — the normal admission path matches before allocating;
        # committing here could duplicate content already parked. Only
        # sharing is lost, never correctness.
        idx = np.full(self.max_blocks_per_seq, self.mgr.num_blocks,
                      np.int32)
        idx[:len(t)] = t
        row = np.full(self.max_blocks_per_seq, self.mgr.num_blocks,
                      np.int32)
        row[:len(t)] = t
        self.cache = _INSTALL_BLOCKS_JIT(
            self.cache, jnp.asarray(idx), payload.k, payload.v,
            payload.k_scale, payload.v_scale,
            jnp.int32(slot), jnp.asarray(row), jnp.int32(payload.cur))
        self.slot_req[slot] = rid
        self.active[slot] = True
        self.is_beam[slot] = False
        self.cur[slot] = payload.cur
        self.gen[slot] = payload.gen
        self.max_gen[slot] = payload.gen + self._remaining(req)
        self.table_len[slot] = len(t)
        self.last_tok[slot] = payload.last_tok
        self.temps[slot] = (self.default_temp if req.temperature is None
                            else req.temperature)
        self.top_ps[slot] = (self.default_top_p if req.top_p is None
                             else req.top_p)
        self._adm_counter += 1
        self.adm_order[slot] = self._adm_counter
        self.slot_aidx[slot] = -1
        # a grammar request resumes mid-stream: the mask state replays
        # the tokens it generated on the prefill replica
        self._bind_grammar(slot, req)
        # empty draft frontier: the decode replica's spec path re-feeds
        # the whole committed sequence through its own draft cache
        self.draft_cur[slot] = 0
        self.slot_k[slot] = self.spec_k
        self._acc_ema[slot] = 1.0
        REQUESTS.event(req, "kv_install", replica=self.trace_name,
                       blocks=payload.n_blocks, cur=payload.cur)
        return True

    # ------------------------------------------------- roofline anatomy
    @contextmanager
    def _tick_timer(self, name: str):
        """Accumulate a named slice of the CURRENT tick's wall time
        (same clock as the tick total, so the breakdown reconciles)."""
        t = time.monotonic()
        try:
            yield
        finally:
            self._tick_phase[name] = (self._tick_phase.get(name, 0.0)
                                      + time.monotonic() - t)

    def _acc_phase(self, phase: str, tokens: int, passes: int, ctx: int):
        """Add one forward's roofline counts to a phase's cumulative
        [seconds, tokens, weight passes, KV-read positions] row (seconds
        arrive separately, from the tick timer in ``step``)."""
        row = self._phase_acc[phase]
        row[1] += tokens
        row[2] += passes
        row[3] += ctx

    def _ctx_blocks(self, mask) -> int:
        """Σ block-rounded attended context over masked slots: the fused
        decode kernel walks whole blocks of the table, so a single-query
        tick reads ceil(len/block)·block positions per slot."""
        lens = self.cur[mask] + 1
        bs = self.block_size
        return int((-(-lens // bs) * bs).sum())

    @staticmethod
    def _ctx_causal(lens, offs) -> int:
        """Σ attended (query, position) pairs of a causal chunk batch:
        a chunk of L tokens at offset O attends L·O + L(L+1)/2 pairs."""
        ls = np.asarray(lens, np.int64)
        os_ = np.asarray(offs, np.int64)
        return int((ls * os_ + ls * (ls + 1) // 2).sum())

    def _push_roofline(self):
        """Fold the cumulative phase accumulators through the roofline
        choke point (lifetime-average MFU/MBU per phase, same cumulative
        convention as the spec acceptance-rate gauge)."""
        if self._geom is None:
            return
        for phase, (sec, tok, passes, ctx) in self._phase_acc.items():
            if sec <= 0.0 or tok <= 0:
                continue
            geom = self._draft_geom if phase == "spec_draft" else self._geom
            if geom is None:
                continue
            record_serving_throughput(
                phase, seconds=sec, tokens=tok, weight_passes=passes,
                kv_read_positions=ctx, geom=geom,
                peak_flops=self._peak_flops, peak_hbm_bps=self._peak_hbm)

    def _refresh_gauges(self, force=False):
        """Point-in-time engine state → gauges (queue depth, active
        slots, KV-pool utilization). Called after every tick and intake
        mutation. ``PT_GAUGE_EVERY_S`` (default 0 = every tick, so dumps
        and tests are unchanged) wall-clock-throttles the sweep for
        host-bound decode loops; drain/finish boundaries and run()-end
        pass ``force=True`` so final gauge values are always exact."""
        if not force:
            try:
                every = float(os.environ.get("PT_GAUGE_EVERY_S", "0") or 0)
            except ValueError:
                every = 0.0
            if every > 0.0 and self._gauge_t is not None \
                    and time.monotonic() - self._gauge_t < every:
                return
        self._gauge_t = time.monotonic()
        self._gauge_sweeps += 1
        if self.async_depth:
            _ASYNC_DEPTH.set(self.async_depth)
        _QUEUE_DEPTH.set(len(self.queue))
        _ACTIVE_SLOTS.set(int(self.active.sum()))
        used = self.mgr.num_blocks - self.mgr.free_blocks
        _KV_IN_USE.set(used)
        _KV_UTIL.set(used / self.mgr.num_blocks if self.mgr.num_blocks
                     else 0.0)
        self.kv.push_prefix_metrics()
        # context parallelism (ISSUE 18): axis size + per-shard block
        # occupancy under the contiguous split. The gauge family stays
        # silent at cp=1 (no shard labels registered) so single-device
        # dumps are byte-identical to pre-cp runs.
        if self.cp > 1:
            _CP_AXIS.set(self.cp)
            ids = (b for t in self.mgr.tables.values() for b in t)
            for s, n in enumerate(shard_occupancy(
                    ids, self.mgr.num_blocks, self.cp)):
                _CP_SHARD_BLOCKS.set(n, shard=str(s))
        led = self.kv.ledger
        if led.enabled:
            led.publish(bytes_per_block=self._kv_block_bytes(),
                        resident_tokens=self._resident_tokens())
            # HBM gauges ship continuously, but the jax query is not
            # tick-cheap — refresh at most once a second (and on the
            # first sweep, so short runs still export them)
            now = time.monotonic()
            if self._dev_mem_t is None or now - self._dev_mem_t >= 1.0:
                self._dev_mem_t = now
                try:
                    device_memory_stats()
                except Exception:
                    pass
        GOODPUT.refresh_gauge()
        # degradation control loop: the gauge sweep doubles as the poll
        # cadence. A router-owned controller is polled by the router
        # only, so N replicas sharing it don't multiply the hysteresis
        # clock by N.
        if self.degrade is not None and self.degrade.owner in (None, self):
            self.degrade.poll()
        # SLO burn-rate sweep rides the same cadence and the same
        # ownership protocol (a Router-claimed tracker is polled by the
        # router only)
        if self.slo is not None and self.slo.owner in (None, self):
            self.slo.poll()
        self._push_roofline()

    def _kv_block_bytes(self) -> int:
        """HBM bytes one pool block holds across all layers (K and V,
        plus the scale pools of a quantized cache) — the actual stored
        dtypes, so ``serving_kv_bytes_per_token`` reports int8 pools at
        their true (halved) footprint."""
        if self._block_bytes is None:
            try:
                self._block_bytes = cache_block_bytes(self.cache)
            except Exception:
                self._block_bytes = 0
        return self._block_bytes

    def _resident_tokens(self) -> int:
        """Tokens whose KV currently sits in the pool (active slots'
        cache frontiers + consumed chunk-prefill spans)."""
        return (int(self.cur[self.active].sum())
                + sum(c for _, c in self.prefilling.values()))

    def step(self):
        """One engine tick — see :meth:`_step_impl`. Wrapped here so the
        tick lands in the trace timeline and the tick-duration histogram
        even when a chaos rule or a dry pool raises out of the middle.
        The tick's anatomy (prefill/draft/verify/sample slices timed by
        :meth:`_tick_timer`, host = the remainder) goes to the breakdown
        histogram: all five phases observe every tick, so the five
        observations sum to the tick's total by construction."""
        t0 = time.monotonic()
        self._tick_phase = {}
        try:
            with _span("serving.step"):
                return self._step_impl()
        finally:
            total = time.monotonic() - t0
            ph = self._tick_phase
            timed = sum(ph.values())
            for name in ("prefill", "draft", "verify", "sample"):
                _TICK_BREAKDOWN.observe(ph.get(name, 0.0), phase=name)
            _TICK_BREAKDOWN.observe(max(0.0, total - timed), phase="host")
            _TICK.observe(total)
            # usage metering (ISSUE 19): bill this tick's device time
            # and KV occupancy to the tenants holding state — the same
            # `total` the histogram just observed, so the ledger's
            # device-seconds reconcile with serving_tick_seconds
            # tick-for-tick
            if self.slo is not None:
                self.slo.charge_tick(self, total)
            acc = self._phase_acc
            acc["prefill"][0] += ph.get("prefill", 0.0)
            acc["spec_draft"][0] += ph.get("draft", 0.0)
            acc["spec_verify"][0] += ph.get("verify", 0.0)
            acc["decode"][0] += ph.get("sample", 0.0)
            # overlap-aware anatomy (ISSUE 20): host work done under an
            # in-flight device dispatch was folded into the "sample"
            # slice above (it is device-overlapped wall time, mirroring
            # PR 4's overlap-aware MFU) — surface it separately here so
            # "host" reports only EXPOSED host time while the five-phase
            # sum still equals the tick total
            if self.async_depth:
                _TICK_HIDDEN.observe(self._hidden_acc)
                self._hidden_acc = 0.0
            force, self._gauge_force = self._gauge_force, False
            self._refresh_gauges(force=force)

    def _step_impl(self):
        """Exception-atomicity shim around :meth:`_step_inner` for the
        async pipeline (ISSUE 20): a fault raised mid-tick while
        dispatched-but-undrained ticks are in flight must not strand
        their tokens — drain the window (their emissions are exactly the
        tokens the synchronous engine produced in the preceding ticks,
        so the stream stays bit-identical), then re-raise. With an empty
        window this adds nothing to the sync path."""
        try:
            return self._step_inner()
        except BaseException:
            if self._async_win:
                self._drain_async("exception")
            raise

    # ------------------------------- async pipelined decode (ISSUE 20)
    def _spec_would_run(self) -> bool:
        """Mirror of the sync tick's speculative-decode gate: True when
        the next tick would draft-and-verify (host sampling every tick —
        the window must drain for it)."""
        return (self.draft_model is not None
                and os.environ.get("PT_SPEC_DECODE", "1") != "0"
                and (self.degrade is None or self.degrade.spec_enabled())
                and bool((self.active & ~self.is_beam
                          & (self.max_gen - self.gen >= 2)).any()))

    def _async_block_reason(self):
        """Why the NEXT tick cannot cruise in the async pipeline — None
        means pure decode (dispatch without fetching). Any non-None
        reason drains the window first, then the tick runs the ordinary
        synchronous path, so block-table mutations, host sampling, and
        the ledger stay tick-exact:

        mode     prefill-only replica / context-parallel engine
        admit    requests waiting for admission (scheduler runs host-side)
        prefill  chunked prefill in flight
        beam     beam groups need host select+fork every tick
        finish   no plain active slots (drain emits the tail, run() ends)
        grammar  constrained slots need the host automaton per token
        adapter  multi-LoRA rows compose per-slot corrections host-side
        window   sliding-window recycling mutates tables per tick
        spec     draft-and-verify samples on the host this tick
        growth   a slot would cross a block boundary within the window
        """
        if self.prefill_only or self.cp > 1:
            return "mode"
        if self.queue:
            return "admit"
        if self.prefilling:
            return "prefill"
        if self.groups or self.is_beam.any():
            return "beam"
        act = self.active & ~self.is_beam
        if not act.any():
            return "finish"
        if self._grammar:
            return "grammar"
        if self._adapter_pins:
            return "adapter"
        if self.window is not None:
            return "window"
        if self._spec_would_run():
            return "spec"
        # the host ``cur`` mirror lags by the window length: the tick
        # about to dispatch writes position cur + len(win), which must
        # already have a table entry (cruise never mutates tables)
        d = len(self._async_win)
        if (((self.cur[act] + d) // self.block_size)
                >= self.table_len[act]).any():
            return "growth"
        return None

    def _async_step(self):
        """One cruise tick of the depth-K pipeline: dispatch the next
        decode tick with the PREVIOUS tick's token array still on device
        (no fetch-reupload round trip), then — once the window exceeds
        ``async_depth`` — fetch and emit the OLDEST tick's tokens, hidden
        under the in-flight dispatch. EOS/max-gen stop is evaluated
        inside the tick jit via the device stop mask, so a slot that
        finished at tick N is masked out of tick N+1 even though the
        host has not seen its token yet."""
        act = self.active & ~self.is_beam
        # chaos parity with the sync tick: these sites fire BEFORE the
        # dispatch, so an injected exception aborts with the cache,
        # tables, and ledger untouched (the shim drains the window)
        if self._is_moe:
            fault_point("serving.moe_dispatch", engine=self,
                        slots=np.nonzero(act)[0])
        if self.exe.cache.k_scales:
            fault_point("serving.kv_quant", engine=self,
                        slots=np.nonzero(act)[0])
        dev = self._async_dev
        if dev is None:
            # window start: seed the device-resident loop state from the
            # host mirrors (exact — the window was just drained)
            dev = self._async_dev = {
                "tokens": jnp.asarray(self.last_tok),
                "stop": jnp.zeros(self.num_slots, bool),
                "gen": jnp.asarray(self.gen),
                "max_gen": jnp.asarray(self.max_gen),
            }
        eos = -1 if self.eos_token_id is None else int(self.eos_token_id)
        rng_before = self.exe.rng
        t0 = time.perf_counter()
        with self._tick_timer("sample"):
            nxt, ran, stop, gen = self.exe.decode_tick_async(
                dev["tokens"], jnp.asarray(act), dev["stop"], dev["gen"],
                dev["max_gen"], self.temps, self.top_ps, eos)
        self.stats["device_s"] += time.perf_counter() - t0
        dev["tokens"], dev["stop"], dev["gen"] = nxt, stop, gen
        self._async_rewound = False
        self._async_win.append(
            {"nxt": nxt, "ran": ran, "rng_before": rng_before})
        self.stats["ticks"] += 1
        emitted = []
        if len(self._async_win) > self.async_depth:
            # steady state: drain exactly the oldest tick. The guard
            # keeps a stream-callback cancel() from recursively draining
            # the window out from under us (it detaches immediately; the
            # dead slot's in-flight rows bill GOODPUT async_overrun).
            self._async_draining = True
            try:
                emitted += self._drain_one()
            finally:
                self._async_draining = False
        return emitted

    def _drain_one(self):
        """Fetch + emit the oldest dispatched tick. The host mirrors
        (``cur``/``gen``/``last_tok``) advance HERE, at drain — so at
        every drain boundary they hold exactly the values the
        synchronous engine would. A fully-masked tick (every slot
        stopped on device before the host noticed) emits nothing and
        rewinds the executor rng to its pre-split state: the sync engine
        never ran that tick, so it never consumed that key."""
        e = self._async_win.pop(0)
        t0 = time.monotonic()
        nxt = np.asarray(e["nxt"])
        ran = np.asarray(e["ran"])
        t1 = time.monotonic()
        # the fetch blocks until that tick's device work completes:
        # device-overlapped wall time, billed to the "sample" slice
        self._tick_phase["sample"] = (self._tick_phase.get("sample", 0.0)
                                      + t1 - t0)
        self.stats["device_s"] += t1 - t0
        if not ran.any():
            if not self._async_rewound:
                self.exe.rng = e["rng_before"]
                self._async_rewound = True
            return []
        # roofline billed at drain, where cur is tick-exact: one weight
        # pass, each ran slot read its block-rounded context (same
        # accounting as the sync tick)
        self._acc_phase("decode", int(ran.sum()), 1, self._ctx_blocks(ran))
        live = ran & (self.slot_req >= 0)
        over = int(ran.sum() - live.sum())
        if over:
            # rows that ran on device for a slot the host has since torn
            # down (cancel from a stream callback mid-window): the sync
            # engine never computed these tokens — wasted work, never
            # emitted
            GOODPUT.waste("async_overrun", over)
        self.cur += live
        t2 = time.monotonic()
        emitted = []
        for slot in np.nonzero(live)[0]:
            emitted += self._emit(int(slot), int(nxt[slot]))
        t3 = time.monotonic()
        self.stats["host_s"] += t3 - t2
        if self._async_win:
            # successors are still in flight: this host work is hidden
            # under device dispatch. Fold it into the "sample" slice
            # (device-overlapped time) and surface it in the hidden-host
            # histogram; the final entry's emit is exposed host time and
            # falls through to the "host" remainder.
            self._hidden_acc += t3 - t2
            self._tick_phase["sample"] = (
                self._tick_phase.get("sample", 0.0) + t3 - t2)
        return emitted

    def _drain_async(self, why: str):
        """Drain the whole window (fetch + emit every dispatched tick),
        leaving the host mirrors tick-exact and the device loop state
        discarded (the next cruise re-seeds from the mirrors). No-op
        when the window is empty or a drain is already on the stack
        (stream-callback re-entrancy)."""
        if not self._async_win or self._async_draining:
            return []
        self._async_draining = True
        try:
            emitted = []
            while self._async_win:
                emitted += self._drain_one()
            self._async_dev = None
            _ASYNC_DRAINS.inc(why=why)
            self._gauge_force = True
            return emitted
        finally:
            self._async_draining = False

    def _step_inner(self):
        """One engine tick: advance in-flight beam groups (select + fork,
        or their final selection), admit waiting requests into free slots
        (their prefill runs now, interleaved with decode), then one decode
        tick for every active slot. Returns [(req_id, new_token), ...]
        (a finishing beam request emits its whole best hypothesis)."""
        # chaos hooks: serving.tick may raise/stall; serving.preempt rules
        # receive the engine and typically call engine._preempt() to
        # induce a preemption the pool never asked for
        fault_point("serving.tick", engine=self)
        fault_point("serving.preempt", engine=self)
        self._expire()
        emitted = []
        if self.async_depth:
            why = self._async_block_reason()
            if why is None:
                return self._async_step()
            if self._async_win:
                # boundary: land every in-flight tick before the host
                # mutates tables/slots — the drained emissions belong to
                # this step's return
                emitted += self._drain_async(why)
        for rid in list(self.groups):
            emitted += self._beam_advance(rid, self.groups[rid])
        admits, beam_admits = self._admit()
        with self._tick_timer("prefill"):
            if admits or beam_admits:
                emitted += self._prefill(admits, beam_admits)
            emitted += self._prefill_chunks()
        if self.prefill_only:
            # prefill-role replica: newly activated slots carry their
            # first token; the router extracts them — never decode here
            return emitted
        if not self.active.any():
            return emitted
        # speculative draft-and-verify for eligible slots; the plain
        # one-token tick then covers only what speculation did not handle
        # (beam slots, final-token slots, fallback after an injected
        # verify fault). PT_SPEC_DECODE=0 kills the whole path.
        spec_handled = np.zeros(self.num_slots, bool)
        if (self.draft_model is not None
                and os.environ.get("PT_SPEC_DECODE", "1") != "0"
                and (self.degrade is None or self.degrade.spec_enabled())):
            elig = (self.active & ~self.is_beam
                    & (self.max_gen - self.gen >= 2))
            if elig.any():
                spec_handled, spec_emitted = self._spec_tick(elig)
                emitted += spec_emitted
        run_mask = self.active & ~spec_handled
        if not run_mask.any():
            # every active slot advanced speculatively: the whole point —
            # this tick paid ONE target forward for k+1 positions per slot
            return emitted
        t0 = time.perf_counter()
        if self._is_moe:
            # chaos: a dead expert shard fails the token all_to_all. Fires
            # BEFORE table growth and the donating tick jit, so an injected
            # exception aborts the tick with the cache, tables, and
            # table_len untouched — cancel/free reclaims every block and
            # assert_quiescent stays clean (exception-atomic).
            fault_point("serving.moe_dispatch", engine=self,
                        slots=np.nonzero(run_mask)[0])
        if self.exe.cache.k_scales:
            # chaos: quantize-on-write about to run inside the tick jit
            # (int8 pools only). Fires BEFORE table growth and the
            # donating tick, so an injected exception aborts with pools,
            # scale pools, tables, and the ledger untouched — no leaked
            # blocks, no stale scales (exception-atomic).
            fault_point("serving.kv_quant", engine=self,
                        slots=np.nonzero(run_mask)[0])
        if self.cp > 1:
            # chaos: the decode tick is about to run the cross-shard
            # partial gather (psum merge over cp). Fires BEFORE table
            # growth and the donating tick jit, so an injected exception
            # aborts the tick with the cache, tables, table_len, and the
            # ledger untouched — no leaked blocks, assert_quiescent and
            # reconcile stay clean (exception-atomic).
            fault_point("serving.cp_gather", engine=self,
                        slots=np.nonzero(run_mask)[0])
        rows, cols, vals = self._grow_tables(run_mask & ~self.is_beam)
        # growth may have preempted slots — recompute the mask after it
        run_mask = self.active & ~spec_handled
        # roofline: one weight pass over the batch; every running slot
        # reads its whole block-rounded context and writes one position
        self._acc_phase("decode", int(run_mask.sum()), 1,
                        self._ctx_blocks(run_mask))
        t1 = time.perf_counter()
        d_aidx = np.where(run_mask, self.slot_aidx, -1)
        d_bias = self._grammar_bias_rows(
            [(int(s), int(s)) for s in np.nonzero(run_mask)[0]],
            self.num_slots)
        with self._tick_timer("sample"):
            nxt, logp = self.exe.decode_tick(
                self.last_tok, run_mask, rows, cols, vals, self.temps,
                self.top_ps, bool(self.groups),
                lora=self._lora_arg(d_aidx, 1), bias=d_bias)
            was_active = run_mask.copy()
            nxt = np.asarray(nxt)             # the one per-tick host fetch
        t2 = time.perf_counter()
        if self.cp > 1:
            _CP_GATHER_S.observe(t2 - t1)
        for g in self.groups.values():        # device-resident, lazy gather
            g.logp = logp[np.asarray(g.slots)]
        self.cur += was_active                # vectorised mirrors
        for slot in np.nonzero(was_active & ~self.is_beam)[0]:
            emitted += self._emit(slot, int(nxt[slot]))
        t3 = time.perf_counter()
        self.stats["host_s"] += (t1 - t0) + (t3 - t2)
        self.stats["device_s"] += t2 - t1
        self.stats["ticks"] += 1
        return emitted

    def run(self) -> dict:
        """Drain queue + slots; returns {req_id: generated token list}."""
        while self.has_work():
            self.step()
        # end-of-run gauges must be exact even under PT_GAUGE_EVERY_S
        self._refresh_gauges(force=True)
        return {rid: r.tokens for rid, r in self.requests.items()}
