"""Grammar-constrained decoding: a token-mask automaton (ISSUE 14).

Structured-output serving needs the sampler to emit ONLY tokens that
keep the partial output inside a formal language (a regex, or the JSON
shape a tenant's schema demands). The standard construction (Outlines /
llguidance) is: compile the grammar to a character automaton once, then
for each decoding step compute the set of vocabulary tokens whose
string, consumed from the current automaton state, stays inside the
live states — and mask everything else out of the logits BEFORE
sampling. Greedy, temperature and nucleus sampling then all stay legal
by construction, and the spec-decode accept rule simply consults the
same mask per drafted position (an illegal draft is rejected before the
target law is even looked at).

Everything here is stdlib + numpy: a regex SUBSET (literals, ``.``,
escapes ``\\d \\w \\s`` + negations, char classes with ranges and
``^`` negation, groups, ``|``, ``* + ?`` and ``{m}``/``{m,n}``/
``{m,}`` counters) is parsed to an AST, compiled to a Thompson NFA,
and determinised LAZILY per character with live-state pruning (a DFA
state is dead unless some contained NFA state can still reach an
accept). Token masks are cached per DFA state — the per-step cost
after warmup is one dictionary hit returning a cached bool[V] /
float32[V] bias row.

``json_schema_regex`` maps a small JSON-schema subset (flat objects of
string / integer / number / boolean / enum properties, canonical key
order, no whitespace) onto that regex subset, so schema-constrained
decoding rides the same automaton.
"""
from __future__ import annotations

import numpy as np

_NEG_BIAS = -1e30            # matches the sampler's top-k/top-p cut value


# --------------------------------------------------------------- charsets
class _CharSet:
    """Set of characters, possibly negated (``[^...]``, ``\\D``, ``.``)."""
    __slots__ = ("chars", "negated")

    def __init__(self, chars, negated=False):
        self.chars = frozenset(chars)
        self.negated = bool(negated)

    def __contains__(self, ch):
        return (ch in self.chars) != self.negated


_DIGITS = frozenset("0123456789")
_WORD = frozenset("abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")
_META = set("\\.[](){}|*+?^$")


def regex_escape(s: str) -> str:
    """Escape ``s`` so it matches literally under this parser."""
    return "".join("\\" + c if c in _META else c for c in s)


# ----------------------------------------------------------------- parser
# AST nodes: ("lit", _CharSet) | ("cat", [nodes]) | ("alt", [nodes])
# | ("star", node) | ("plus", node) | ("opt", node)
# | ("rep", node, m, n_or_None)
class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _err(self, msg):
        raise ValueError(f"grammar regex: {msg} at index {self.i} "
                         f"in {self.p!r}")

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self._err(f"unexpected {self.p[self.i]!r}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts = []
        while self._peek() not in (None, "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return ("cat", [])
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def _repeat(self):
        node = self._atom()
        while True:
            ch = self._peek()
            if ch == "*":
                self.i += 1
                node = ("star", node)
            elif ch == "+":
                self.i += 1
                node = ("plus", node)
            elif ch == "?":
                self.i += 1
                node = ("opt", node)
            elif ch == "{":
                node = self._counted(node)
            else:
                return node

    def _counted(self, node):
        j = self.p.find("}", self.i)
        if j < 0:
            self._err("unterminated {…} counter")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        if "," in body:
            lo, hi = body.split(",", 1)
            m = int(lo) if lo else 0
            n = int(hi) if hi else None
        else:
            m = n = int(body)
        if n is not None and n < m:
            self._err(f"bad counter {{{body}}}")
        return ("rep", node, m, n)

    def _atom(self):
        ch = self._peek()
        if ch is None:
            self._err("dangling quantifier or empty atom")
        if ch == "(":
            self.i += 1
            node = self._alt()
            if self._peek() != ")":
                self._err("unclosed group")
            self.i += 1
            return node
        if ch == "[":
            return ("lit", self._char_class())
        if ch == "\\":
            return ("lit", self._escape())
        if ch == ".":
            self.i += 1
            return ("lit", _CharSet("\n", negated=True))
        if ch in "*+?{)":
            self._err(f"unexpected {ch!r}")
        self.i += 1
        return ("lit", _CharSet(ch))

    def _escape(self):
        self.i += 1                       # consume the backslash
        ch = self._peek()
        if ch is None:
            self._err("trailing backslash")
        self.i += 1
        table = {"d": _CharSet(_DIGITS), "D": _CharSet(_DIGITS, True),
                 "w": _CharSet(_WORD), "W": _CharSet(_WORD, True),
                 "s": _CharSet(_SPACE), "S": _CharSet(_SPACE, True),
                 "n": _CharSet("\n"), "t": _CharSet("\t"),
                 "r": _CharSet("\r")}
        return table.get(ch, _CharSet(ch))

    def _char_class(self):
        self.i += 1                       # consume '['
        negated = self._peek() == "^"
        if negated:
            self.i += 1
        chars = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                self._err("unclosed character class")
            if ch == "]" and not first:
                self.i += 1
                return _CharSet(chars, negated)
            first = False
            if ch == "\\":
                sub = self._escape()
                if sub.negated:
                    self._err("negated escape inside a class")
                chars |= sub.chars
                continue
            self.i += 1
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                hi = self.p[self.i + 1]
                self.i += 2
                if ord(hi) < ord(ch):
                    self._err(f"bad range {ch}-{hi}")
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)


# ------------------------------------------------------------ Thompson NFA
class _NFA:
    """States are ints; ``eps[s]`` / ``chars[s]`` hold the out-edges."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.chars: list[list[tuple[_CharSet, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.chars.append([])
        return len(self.eps) - 1

    def emit(self, node) -> tuple[int, int]:
        """Compile an AST node to a (start, end) fragment; ``end`` has no
        out-edges inside the fragment (Thompson invariant)."""
        kind = node[0]
        if kind == "lit":
            s, e = self.state(), self.state()
            self.chars[s].append((node[1], e))
            return s, e
        if kind == "cat":
            s = e = self.state()
            for child in node[1]:
                cs, ce = self.emit(child)
                self.eps[e].append(cs)
                e = ce
            return s, e
        if kind == "alt":
            s, e = self.state(), self.state()
            for child in node[1]:
                cs, ce = self.emit(child)
                self.eps[s].append(cs)
                self.eps[ce].append(e)
            return s, e
        if kind in ("star", "plus", "opt"):
            cs, ce = self.emit(node[1])
            s, e = self.state(), self.state()
            self.eps[s].append(cs)
            self.eps[ce].append(e)
            if kind != "plus":
                self.eps[s].append(e)     # zero occurrences allowed
            if kind != "opt":
                self.eps[ce].append(cs)   # loop back for more
            return s, e
        if kind == "rep":
            _, child, m, n = node
            parts = [("cat", [child] * m)] if m else []
            if n is None:
                parts.append(("star", child))
            else:
                parts.extend([("opt", child)] * (n - m))
            return self.emit(("cat", parts))
        raise AssertionError(f"unknown AST node {kind!r}")

    def productive(self, accept: int) -> frozenset:
        """NFA states from which ``accept`` is reachable — the live set
        for dead-state pruning in the lazy DFA."""
        rev: list[list[int]] = [[] for _ in self.eps]
        for s, outs in enumerate(self.eps):
            for t in outs:
                rev[t].append(s)
        for s, outs in enumerate(self.chars):
            for _, t in outs:
                rev[t].append(s)
        seen = {accept}
        stack = [accept]
        while stack:
            for s in rev[stack.pop()]:
                if s not in seen:
                    seen.add(s)
                    stack.append(s)
        return frozenset(seen)


# --------------------------------------------------------------- automaton
class TokenMaskAutomaton:
    """Per-state token legality for a vocabulary, over a regex subset.

    ``vocab`` is the decoded string of every token id (index = id).
    ``mask(state)`` → cached ``bool[V]`` of legal next tokens;
    ``bias(state)`` → cached ``float32[V]`` additive logit bias (0 legal,
    ``-1e30`` illegal) the sampler adds before temperature/top-k/top-p;
    ``advance(state, tok)`` → successor state after emitting ``tok``.
    EOS is legal exactly when the state is accepting — with one escape
    hatch: if NO vocabulary token is legal from a live state (the vocab
    cannot spell any continuation), EOS is allowed so the sequence
    finishes instead of emitting an illegal token.
    """

    def __init__(self, regex: str = None, *, json_schema=None, vocab,
                 eos_token_id: int = None):
        if (regex is None) == (json_schema is None):
            raise ValueError("pass exactly one of regex / json_schema")
        if json_schema is not None:
            regex = json_schema_regex(json_schema)
        self.pattern = regex
        self.vocab = [str(v) for v in vocab]
        self.eos_token_id = eos_token_id
        nfa = _NFA()
        start, accept = nfa.emit(_Parser(regex).parse())
        self._nfa = nfa
        self._accept = accept
        self._live = nfa.productive(accept)
        # DFA states: frozensets of NFA states, interned to small ints
        s0 = self._closure(frozenset([start]))
        if not (s0 & self._live):
            raise ValueError(f"regex {regex!r} matches nothing")
        self._sets: list[frozenset] = [s0]
        self._ids: dict[frozenset, int] = {s0: 0}
        self._char_memo: dict[tuple[int, str], int] = {}
        self._tok_dest: dict[int, np.ndarray] = {}   # sid -> int32[V]
        self._masks: dict[int, np.ndarray] = {}
        self._biases: dict[int, np.ndarray] = {}
        self.start_state = 0

    # ------------------------------------------------------------ core DFA
    def _closure(self, states: frozenset) -> frozenset:
        stack, seen = list(states), set(states)
        while stack:
            for t in self._nfa.eps[stack.pop()]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    def _step_char(self, sid: int, ch: str) -> int:
        """DFA transition on one character; -1 is the dead state."""
        key = (sid, ch)
        hit = self._char_memo.get(key)
        if hit is not None:
            return hit
        nxt = set()
        for s in self._sets[sid]:
            for cs, t in self._nfa.chars[s]:
                if ch in cs:
                    nxt.add(t)
        out = -1
        if nxt:
            closed = self._closure(frozenset(nxt))
            if closed & self._live:
                out = self._ids.get(closed)
                if out is None:
                    out = len(self._sets)
                    self._sets.append(closed)
                    self._ids[closed] = out
        self._char_memo[key] = out
        return out

    def _token_dests(self, sid: int) -> np.ndarray:
        """Destination DFA state per token id (-1 = illegal), cached."""
        dests = self._tok_dest.get(sid)
        if dests is None:
            dests = np.empty(len(self.vocab), np.int32)
            for tid, text in enumerate(self.vocab):
                cur = sid
                if not text:
                    cur = -1              # zero-progress tokens stall
                for ch in text:
                    cur = self._step_char(cur, ch)
                    if cur < 0:
                        break
                dests[tid] = cur
            self._tok_dest[sid] = dests
        return dests

    # ------------------------------------------------------------- surface
    def accepting(self, sid: int) -> bool:
        return sid >= 0 and self._accept in self._sets[sid]

    def mask(self, sid: int) -> np.ndarray:
        m = self._masks.get(sid)
        if m is None:
            m = self._token_dests(sid) >= 0
            eid = self.eos_token_id
            if eid is not None:
                m = m.copy()
                # EOS: exactly when accepting — or as the only way out
                # of a live state the vocab cannot continue from
                m[eid] = self.accepting(sid) or not m.any()
            m.setflags(write=False)
            self._masks[sid] = m
        return m

    def bias(self, sid: int) -> np.ndarray:
        b = self._biases.get(sid)
        if b is None:
            b = np.where(self.mask(sid), 0.0, _NEG_BIAS).astype(np.float32)
            b.setflags(write=False)
            self._biases[sid] = b
        return b

    def advance(self, sid: int, tok: int) -> int:
        """Successor state after emitting ``tok`` (EOS keeps the state:
        the sequence is finished, nothing further consults it)."""
        if tok == self.eos_token_id:
            return sid
        dest = int(self._token_dests(sid)[tok])
        if dest < 0:
            raise ValueError(
                f"token {tok} ({self.vocab[tok]!r}) is illegal from "
                f"grammar state {sid} of {self.pattern!r}")
        return dest


# ------------------------------------------------------------ JSON schema
def json_schema_regex(schema: dict) -> str:
    """Map a flat JSON-schema subset onto the regex subset above:
    ``object`` with string/integer/number/boolean/enum properties
    (canonical = declaration order, every property present, no
    whitespace), plus the same leaf types standalone."""
    def leaf(spec):
        if "enum" in spec:
            opts = []
            for v in spec["enum"]:
                if isinstance(v, str):
                    opts.append('"' + regex_escape(v) + '"')
                elif isinstance(v, bool):
                    opts.append("true" if v else "false")
                else:
                    opts.append(regex_escape(repr(v)))
            return "(" + "|".join(opts) + ")"
        t = spec.get("type")
        if t == "string":
            return '"[^"]*"'
        if t == "integer":
            return "-?\\d+"
        if t == "number":
            return "-?\\d+(\\.\\d+)?"
        if t == "boolean":
            return "(true|false)"
        raise ValueError(f"unsupported schema leaf: {spec!r}")

    if schema.get("type") == "object":
        props = schema.get("properties", {})
        if not props:
            return "\\{\\}"
        fields = ['"' + regex_escape(k) + '":' + leaf(v)
                  for k, v in props.items()]
        return "\\{" + ",".join(fields) + "\\}"
    return leaf(schema)
