"""Multi-replica serving router (ISSUE 7).

In-process front end over N :class:`LLMEngine` replicas — the layer the
ROADMAP's "millions of users" north-star enters through. Three jobs:

  * **Dispatch** — least-outstanding-requests across healthy replicas,
    with session affinity (requests sharing a ``session_id`` stick to
    one replica so a session's prefix-cache blocks stay local) and
    per-replica health gating: a replica whose
    :class:`~paddle_tpu.observability.health.HealthEvaluator` verdict is
    CRIT (or that the router declared dead) receives nothing.
  * **Rebalancing** — ``drain_replica`` requeues the draining replica's
    waiting requests BEFORE draining it (otherwise affinity-pinned work
    the router holds for it would wait forever — the drain deadlock);
    a replica death pulls every live request back and re-dispatches it
    to a healthy replica exactly once.
  * **Disaggregated prefill/decode** — DistServe/Splitwise-style roles:
    ``role="prefill"`` replicas run admission + (chunked) prefill only,
    then each finished sequence is extracted and installed into a
    ``role="decode"`` replica through the
    :class:`~paddle_tpu.serving.transfer.KVTransfer` seam. Greedy
    output is identical to a single-engine run. ``PT_ROUTER_DISAGG=0``
    is the kill switch: roles collapse to "both" and every replica
    serves end-to-end.

Graceful degradation (ISSUE 16) adds the reaction layer: an optional
shared :class:`~paddle_tpu.serving.degrade.DegradationController`
(polled once per step; L4 rejects new sessions here with
``OverloadError``), periodic host-side session snapshots
(``snapshot_every``) that restore a request onto a surviving replica
after a *second* replica death instead of failing it, and a hardened
handoff transport (:class:`~paddle_tpu.serving.transfer.TransportPolicy`)
— per-attempt geometry+checksum validation with bounded retries, plus
straggler hedging to another decode replica when a delivery blows its
p95-derived deadline (first install wins; the loser copy is dropped
without ever touching a pool).

The router is deliberately single-threaded per ``step()`` — replicas
advance in one round-robin sweep, which keeps the chaos sites
(``router.dispatch``, ``router.kv_transfer``, ``router.kv_stall``,
``router.kv_partial``, ``router.replica_death``) deterministic. ``run(parallel=True)`` is the throughput mode: one
driver thread per replica free-runs its engine (pure scale-out; used by
the bench), falling back to sequential rounds when disaggregation or
router-level work needs the orchestration loop.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

import numpy as np

from paddle_tpu.observability import span as _span
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.health import (HEALTH, HealthEvaluator,
                                             gauge_imbalance)
from paddle_tpu.observability.requests import REQUESTS
from paddle_tpu.serving.engine import LLMEngine
from paddle_tpu.serving.telemetry import (_R_DEATHS, _R_DISPATCH,
                                          _R_HEALTH, _R_HEDGE_RATE,
                                          _R_HEDGES, _R_OUTSTANDING,
                                          _R_REQUEUES, _R_RESTORES,
                                          _R_TRANSFER_BLOCKS,
                                          _R_TRANSFER_RETRIES,
                                          _R_TRANSFER_SECONDS,
                                          _R_TRANSFERS, _REJECTED,
                                          _TENANT_FINISHED,
                                          _TENANT_REJECTED, tenant_label)
from paddle_tpu.serving.transfer import (DeviceKVTransfer, KVTransferError,
                                         TransportPolicy, validate_payload)
from paddle_tpu.serving.types import (EngineDrainingError, OverloadError,
                                      QueueFullError, Request)
from paddle_tpu.utils.faults import fault_point

_VERDICT_NUM = {"OK": 0, "WARN": 1, "CRIT": 2}


class Replica:
    """One engine behind the router: a name, a role, and a health
    evaluator whose verdict gates dispatch. ``role`` is "both" (serve
    end-to-end), "prefill", or "decode" (disaggregated)."""

    def __init__(self, engine: LLMEngine, name: str = None,
                 role: str = "both", health: HealthEvaluator = None):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self.engine = engine
        self.name = name
        self.role = role
        # default evaluator has no rules -> always OK; tests/deployments
        # attach per-replica rules (e.g. on that replica's gauges)
        self.health = health if health is not None else HealthEvaluator()
        self.alive = True
        self.draining = False

    def verdict(self) -> str:
        if not self.alive:
            return "CRIT"
        try:
            return self.health.evaluate()["status"]
        except Exception:
            return "CRIT"        # an unevaluable replica is not dispatchable


class Router:
    """Least-outstanding-requests front end over N engine replicas."""

    def __init__(self, replicas, *, affinity=True, max_queue_len=None,
                 kv_transfer=None, install_imbalance_rule=True,
                 degrade=None, slo=None, snapshot_every=None,
                 max_session_restores=4, transport=None, clock=None):
        self.replicas: list[Replica] = []
        for i, r in enumerate(replicas):
            if not isinstance(r, Replica):
                r = Replica(r)
            if r.name is None:
                r.name = f"r{i}"
            self.replicas.append(r)
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        for r in self.replicas:
            # request-tracker events carry the replica name; the tracker
            # stitches cross-replica timelines on it (ISSUE 9)
            r.engine.trace_name = r.name
        # kill switch: PT_ROUTER_DISAGG=0 collapses roles to "both" — one
        # env flip turns a misbehaving disaggregated deployment into
        # plain replicated serving without touching the topology
        self.disagg = (any(r.role != "both" for r in self.replicas)
                       and os.environ.get("PT_ROUTER_DISAGG", "1") != "0")
        if not self.disagg:
            for r in self.replicas:
                r.role = "both"
                r.engine.prefill_only = False
        else:
            if not any(r.role in ("both", "decode") for r in self.replicas):
                raise ValueError("disaggregated topology has no decode-"
                                 "capable replica (role both/decode)")
            bs = {r.engine.block_size for r in self.replicas}
            if len(bs) != 1:
                raise ValueError(f"replicas disagree on block_size: {bs}")
            for r in self.replicas:
                r.engine.prefill_only = (r.role == "prefill")
        self.affinity = bool(affinity)
        self.kv_transfer = (kv_transfer if kv_transfer is not None
                            else DeviceKVTransfer())
        # hardened handoff transport (ISSUE 16): deadline + bounded
        # retries + straggler hedging around ship/validate/install
        self.transport = (transport if transport is not None
                          else TransportPolicy())
        self._clock = clock if clock is not None else time.monotonic
        # graceful degradation: one shared controller for the fleet —
        # the router claims it (owner) and polls it once per step from
        # the gauge sweep; replica engines consult its effect queries
        # but never advance its hysteresis clocks
        self.degrade = degrade
        if degrade is not None:
            degrade.owner = self
            for r in self.replicas:
                if r.engine.degrade is None:
                    r.engine.degrade = degrade
        # per-tenant SLO tracker + cost ledger (ISSUE 19): same owner
        # protocol as the ladder — the router claims the tracker and
        # polls it once per step so N replicas don't multiply the
        # alerting cadence; engines still charge their own ticks
        self.slo = slo
        if slo is not None:
            slo.owner = self
            for r in self.replicas:
                if r.engine.slo is None:
                    r.engine.slo = slo
        # session durability: periodic host-side snapshots every N
        # steps. None/0 = OFF — the legacy contract (a request's second
        # replica death fails it) stays the default
        self.snapshot_every = snapshot_every
        self.max_session_restores = max_session_restores
        self._snapshots: dict[int, object] = {}   # rid -> SessionSnapshot
        self._restores: dict[int, int] = {}       # rid -> restore count
        self._step_i = 0
        self.max_queue_len = max_queue_len
        self._queue: deque[Request] = deque()     # awaiting dispatch
        self.requests: dict[int, Request] = {}    # every request ever seen
        self._where: dict[int, int] = {}          # rid -> replica index
        self._sessions: dict[tuple, int] = {}     # (stage, sid) -> index
        self._pending: list = []                  # KVPayloads to install
        self._requeued: set[int] = set()          # death-requeue, ONCE each
        self._ids = itertools.count()
        self.stats = {"dispatched": 0, "requeues": 0, "transfers": 0,
                      "deaths": 0, "rejected": 0, "hedges": 0}
        if install_imbalance_rule:
            # stock rule on the process-global evaluator: flags one
            # replica hoarding outstanding requests (LOR should keep the
            # spread near 0; a big spread means gating/affinity gone bad)
            HEALTH.rule(
                "router_replica_imbalance",
                gauge_imbalance("router_replica_outstanding"),
                warn=2.0, crit=8.0,
                description="(max-min)/mean outstanding requests across "
                            "replicas — sustained spread means dispatch "
                            "is not balancing")

    # ------------------------------------------------------------- intake
    def add_request(self, req: Request) -> int:
        """Accept a request and dispatch it immediately when a healthy
        replica can take it (the common path); otherwise it waits in the
        router queue for the next ``step``."""
        if not any(r.alive and not r.draining for r in self.replicas):
            self.stats["rejected"] += 1
            raise EngineDrainingError(
                "no live replica is accepting work (all dead or draining)")
        if (self.max_queue_len is not None
                and len(self._queue) >= self.max_queue_len):
            self.stats["rejected"] += 1
            raise QueueFullError(
                f"router queue full ({self.max_queue_len} waiting) — "
                "shed load or retry later")
        # ladder L4: explicit backpressure on NEW sessions — in-flight
        # work keeps running and finishes; only intake is refused
        if (self.degrade is not None
                and not self.degrade.accepting_sessions()):
            self.stats["rejected"] += 1
            _REJECTED.inc(reason="degraded")
            if req.tenant_id is not None:
                _TENANT_REJECTED.inc(tenant=tenant_label(req.tenant_id))
            raise OverloadError(
                "degradation ladder at L4 — new sessions rejected, "
                "retry after the cluster recovers")
        if req.req_id is None:
            req.req_id = next(self._ids)
        else:
            if req.req_id in self.requests:
                raise ValueError(f"req_id {req.req_id} already exists")
            self._ids = itertools.count(
                max(req.req_id + 1, next(self._ids)))
        self.requests[req.req_id] = req
        # the router's intake gate is THE session gate for the fleet —
        # replica engines skip theirs for router-owned work, so L4
        # never re-rejects an accepted request mid-dispatch or requeue
        req._preadmitted = True
        REQUESTS.submit(req, source="router")
        self._queue.append(req)
        self._flush_queue()
        return req.req_id

    def generate(self, prompt, **kw) -> int:
        return self.add_request(Request(prompt, **kw))

    def _forget(self, rid: int):
        """Drop all per-request router state once a request is done."""
        self._where.pop(rid, None)
        self._snapshots.pop(rid, None)
        self._restores.pop(rid, None)

    def pop_finished(self) -> dict:
        done = {rid: r for rid, r in self.requests.items() if r.done}
        for rid in done:
            del self.requests[rid]
            self._requeued.discard(rid)
            self._forget(rid)
        return done

    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._pending)
                or any(r.alive and r.engine.has_work()
                       for r in self.replicas))

    def cancel(self, rid: int, reason: str = "cancelled") -> bool:
        """Cancel wherever the request lives: router queue, in-flight
        KV handoff, or a replica engine."""
        req = self.requests.get(rid)
        if req is None or req.done:
            return False
        for i, q in enumerate(self._queue):
            if q.req_id == rid:
                del self._queue[i]
                req.done = True
                req.finish_reason = reason
                self._forget(rid)
                REQUESTS.finish(req, reason)
                return True
        for j, p in enumerate(self._pending):
            if p.req.req_id == rid:
                del self._pending[j]
                req.done = True
                req.finish_reason = reason
                self._forget(rid)
                REQUESTS.finish(req, reason)
                return True
        i = self._where.get(rid)
        if i is not None:
            out = self.replicas[i].engine.cancel(rid, reason)
            if out:
                self._forget(rid)
            return out
        return False

    # ----------------------------------------------------------- dispatch
    def _candidates(self, req: Request) -> list:
        """Replica indices eligible for this request's ADMISSION: alive,
        not draining, verdict below CRIT, role-compatible. Disaggregated:
        admission goes to prefill-role replicas — except beam requests,
        which cannot be extracted mid-flight and therefore run end-to-end
        on a decode-capable replica."""
        if self.disagg:
            roles = (("both", "decode") if req.num_beams > 1
                     else ("prefill", "both"))
        else:
            roles = ("both",)
        return [i for i, r in enumerate(self.replicas)
                if r.role in roles and r.alive and not r.draining
                and r.verdict() != "CRIT"]

    def _pick(self, req: Request, cands: list) -> int:
        if self.affinity and req.session_id is not None:
            pinned = self._sessions.get(("admit", req.session_id))
            if pinned in cands:
                return pinned
        # least outstanding requests; index breaks ties deterministically
        return min(cands, key=lambda i:
                   (self.replicas[i].engine.outstanding(), i))

    def _dispatch(self, req: Request) -> bool:
        """Hand one request to a replica. Returns False when it must stay
        with the router (no candidate, per-engine backpressure from every
        candidate, or an injected dispatch fault)."""
        cands = self._candidates(req)
        while cands:
            i = self._pick(req, cands)
            rep = self.replicas[i]
            try:
                # chaos fires BEFORE the engine sees the request, so an
                # injected exception leaves both sides untouched — the
                # request simply stays queued with the router
                fault_point("router.dispatch", router=self,
                            rid=req.req_id, replica=rep.name)
                with _span("router.dispatch", replica=rep.name,
                           rid=req.req_id):
                    rep.engine.add_request(req)
            except (QueueFullError, EngineDrainingError):
                cands.remove(i)          # replica-local backpressure:
                continue                 # try the next-least-loaded one
            except Exception as e:
                self.stats["requeues"] += 1
                _R_REQUEUES.inc(replica=rep.name, why="dispatch_fault")
                FLIGHT.record("router.requeue", rid=req.req_id,
                              replica=rep.name, why="dispatch_fault",
                              error=f"{type(e).__name__}: {e}")
                REQUESTS.event(req, "requeued", replica=rep.name,
                               why="dispatch_fault")
                return False
            self._where[req.req_id] = i
            if self.affinity and req.session_id is not None:
                self._sessions[("admit", req.session_id)] = i
            self.stats["dispatched"] += 1
            _R_DISPATCH.inc(replica=rep.name)
            REQUESTS.event(req, "dispatched", replica=rep.name)
            return True
        return False

    def _flush_queue(self):
        """FCFS: dispatch from the head until a request can't go
        anywhere (it stays at the head — no starvation, no reordering
        of a session's requests)."""
        while self._queue:
            req = self._queue[0]
            if req.done:                 # cancelled while waiting
                self._queue.popleft()
                continue
            self._queue.popleft()
            if not self._dispatch(req):
                self._queue.appendleft(req)
                break

    # ----------------------------------------------- disaggregated handoff
    def _collect_prefilled(self):
        """Extract every sequence a prefill-role replica has finished
        prefilling (its slot is ACTIVE, first token emitted, but the
        engine will never decode it). The ``router.kv_transfer`` chaos
        site fires before extraction: an injected failure pulls the
        request back to the router queue — re-prefilled elsewhere from
        its resume form, so greedy output is unchanged and no blocks
        leak on either replica."""
        for rep in self.replicas:
            if rep.role != "prefill" or not rep.alive:
                continue
            eng = rep.engine
            for slot in np.nonzero(eng.active & ~eng.is_beam)[0]:
                rid = int(eng.slot_req[slot])
                req = eng.requests.get(rid)
                if req is None or req.done:
                    continue
                try:
                    fault_point("router.kv_transfer", router=self,
                                rid=rid, replica=rep.name)
                    with _span("router.kv_transfer", rid=rid,
                               src=rep.name):
                        payload = eng.extract_sequence(rid)
                except (ValueError, NotImplementedError):
                    raise                # real extraction bug: surface it
                except Exception as e:
                    pulled = eng.release_request(rid)
                    if pulled is not None:
                        if pulled.tokens:
                            pulled._resume = np.concatenate(
                                [pulled.prompt,
                                 np.asarray(pulled.tokens, np.int32)])
                        self._queue.appendleft(pulled)
                        self._where.pop(rid, None)
                        self.stats["requeues"] += 1
                        _R_REQUEUES.inc(replica=rep.name, why="kv_transfer")
                        FLIGHT.record("router.requeue", rid=rid,
                                      replica=rep.name, why="kv_transfer",
                                      error=f"{type(e).__name__}: {e}")
                        REQUESTS.event(pulled, "requeued", replica=rep.name,
                                       why="kv_transfer")
                    continue
                self._pending.append(payload)
                self._where.pop(rid, None)

    def _deliver(self, payload, rep):
        """One validated delivery of ``payload`` to ``rep``: the
        ``router.kv_stall`` chaos window (straggler delay), ship, the
        ``router.kv_partial`` corruption window (a rule action returns
        a corrupted REPLACEMENT — the source payload stays pristine),
        then geometry+checksum validation. Failed attempts retry with
        bounded exponential backoff up to ``transport.max_attempts``.
        Returns the validated shipped payload, or None when every
        attempt failed (the payload stays pending; nothing was
        installed)."""
        rid = payload.req.req_id
        for attempt in range(self.transport.max_attempts):
            if attempt:
                self.transport.sleep(self.transport.backoff_s(attempt - 1))
            try:
                fault_point("router.kv_stall", router=self, rid=rid,
                            replica=rep.name, attempt=attempt)
                shipped = self.kv_transfer.ship(payload, rep.engine)
                alt = fault_point("router.kv_partial", router=self,
                                  rid=rid, replica=rep.name,
                                  attempt=attempt, payload=shipped)
                if alt is not None:
                    shipped = alt
                validate_payload(shipped, rep.engine)
                return shipped
            except EngineDrainingError:
                raise
            except Exception as e:
                why = ("partial" if isinstance(e, KVTransferError)
                       else "error")
                _R_TRANSFER_RETRIES.inc(replica=rep.name, why=why)
                FLIGHT.record("router.kv_retry", rid=rid,
                              replica=rep.name, attempt=attempt, why=why,
                              error=f"{type(e).__name__}: {e}")
        return None

    def _installed(self, payload, i: int):
        """Common bookkeeping once a payload's install succeeded."""
        req = payload.req
        rep = self.replicas[i]
        self._where[req.req_id] = i
        if self.affinity and req.session_id is not None:
            self._sessions[("decode", req.session_id)] = i
        self.stats["transfers"] += 1
        _R_TRANSFERS.inc()
        _R_TRANSFER_BLOCKS.inc(payload.n_blocks)
        REQUESTS.event(req, "kv_ship", replica=rep.name,
                       blocks=payload.n_blocks)

    def _hedge(self, payload, slow_i: int, others: list,
               elapsed: float, deadline: float) -> bool:
        """Straggler hedging: the primary delivery blew its deadline,
        so re-dispatch the handoff to the next-least-loaded decode
        replica. First copy to INSTALL wins; returns True when the
        hedge won — the slow primary copy is then dropped without ever
        being installed (the exactly-once loser cancellation: no slot,
        no blocks, no second registration). Returns False to fall back
        to the late primary copy."""
        req = payload.req
        j = min(others, key=lambda x:
                (self.replicas[x].engine.outstanding(), x))
        hrep = self.replicas[j]
        self.stats["hedges"] += 1
        _R_HEDGES.inc()
        FLIGHT.record("router.kv_hedge", rid=req.req_id,
                      slow=self.replicas[slow_i].name, hedge=hrep.name,
                      elapsed_s=round(elapsed, 6),
                      deadline_s=round(deadline, 6))
        t0 = self._clock()
        try:
            shipped = self._deliver(payload, hrep)
            if shipped is None or not hrep.engine.install_sequence(shipped):
                return False
        except EngineDrainingError:
            return False
        _R_TRANSFER_SECONDS.observe(self._clock() - t0)
        FLIGHT.record("router.kv_hedge_win", rid=req.req_id,
                      replica=hrep.name)
        REQUESTS.event(req, "kv_hedged", replica=hrep.name)
        self._installed(payload, j)
        return True

    def _flush_pending(self):
        """Install extracted sequences into decode-capable replicas (LOR
        with decode-stage affinity) through the hardened transport:
        per-attempt validation + bounded retries (:meth:`_deliver`),
        and straggler hedging when the primary delivery exceeds the
        policy deadline (p95-derived by default). A payload that fits
        nowhere right now simply waits — slots/blocks free up as
        decodes finish."""
        still = []
        for payload in self._pending:
            req = payload.req
            cands = [i for i, r in enumerate(self.replicas)
                     if r.role in ("both", "decode") and r.alive
                     and not r.draining and r.verdict() != "CRIT"]
            if self.affinity and req.session_id is not None:
                pinned = self._sessions.get(("decode", req.session_id))
                if pinned in cands:
                    cands = [pinned]
            if not cands:
                still.append(payload)
                continue
            i = min(cands, key=lambda j:
                    (self.replicas[j].engine.outstanding(), j))
            rep = self.replicas[i]
            deadline = self.transport.deadline(_R_TRANSFER_SECONDS)
            t0 = self._clock()
            try:
                with _span("router.kv_transfer", rid=req.req_id,
                           dst=rep.name):
                    shipped = self._deliver(payload, rep)
            except EngineDrainingError:
                still.append(payload)
                continue
            elapsed = self._clock() - t0
            if shipped is None:
                still.append(payload)    # retries exhausted this step
                continue
            if (self.transport.hedge and deadline is not None
                    and elapsed > deadline):
                others = [j for j in cands if j != i]
                if others and self._hedge(payload, i, others,
                                          elapsed, deadline):
                    continue             # hedge won; slow copy dropped
            try:
                ok = rep.engine.install_sequence(shipped)
            except EngineDrainingError:
                still.append(payload)
                continue
            if not ok:
                still.append(payload)    # no slot/blocks free yet
                continue
            _R_TRANSFER_SECONDS.observe(elapsed)
            self._installed(payload, i)
        self._pending = still

    # ------------------------------------------------------ death/drain
    def _replica_death(self, i: int, exc: Exception):
        """Declare replica ``i`` dead: harvest what it finished, pull
        every live request back, and requeue each to a healthy replica
        EXACTLY ONCE — a request whose second replica also dies finishes
        with ``finish_reason="replica_death"`` instead of bouncing
        forever."""
        rep = self.replicas[i]
        rep.alive = False
        self.stats["deaths"] += 1
        _R_DEATHS.inc()
        FLIGHT.record("router.replica_death", replica=rep.name,
                      error=f"{type(exc).__name__}: {exc}")
        eng = rep.engine
        for rid, r in eng.pop_finished().items():
            self._forget(rid)                # finished work is still good
        for rid in list(eng.requests):
            req = eng.release_request(rid)
            self._where.pop(rid, None)
            if req is None:
                continue
            if rid in self._requeued:
                snap = self._snapshots.get(rid)
                restores = self._restores.get(rid, 0)
                if (snap is not None
                        and restores < self.max_session_restores):
                    # session durability (ISSUE 16): the exactly-once
                    # requeue is spent, but a snapshot outlives the
                    # replica — restore instead of failing. Tokens roll
                    # back to the capture point; the resume prefill
                    # replays them through the radix cache (waste billed
                    # as replay_prefill), so greedy output still matches
                    # an undisturbed run.
                    self._restores[rid] = restores + 1
                    req.tokens = list(snap.tokens)
                    req._resume = (snap.resume_ids() if snap.tokens
                                   else None)
                    req._match_memo = None
                    self._queue.appendleft(req)
                    self.stats["requeues"] += 1
                    _R_RESTORES.inc()
                    _R_REQUEUES.inc(replica=rep.name,
                                    why="session_restore")
                    FLIGHT.record("router.session_restore", rid=rid,
                                  replica=rep.name,
                                  tokens=len(snap.tokens))
                    REQUESTS.event(req, "restored", replica=rep.name,
                                   tokens=len(snap.tokens))
                    continue
                req.done = True
                req.finish_reason = "replica_death"
                if req.tenant_id is not None:
                    _TENANT_FINISHED.inc(
                        tenant=tenant_label(req.tenant_id),
                        reason="replica_death")
                self._forget(rid)
                FLIGHT.record("router.requeue_exhausted", rid=rid)
                REQUESTS.finish(req, "replica_death", replica=rep.name)
                continue
            self._requeued.add(rid)
            if req.tokens:
                # resume form: the next replica re-prefills prompt +
                # generated-so-far, continuing bit-exactly under greedy
                req._resume = np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)])
            self._queue.appendleft(req)
            self.stats["requeues"] += 1
            _R_REQUEUES.inc(replica=rep.name, why="replica_death")
            FLIGHT.record("router.requeue", rid=rid, replica=rep.name,
                          why="replica_death")
            REQUESTS.event(req, "requeued", replica=rep.name,
                           why="replica_death")
        # affinity pins to a dead replica are meaningless — unpin so the
        # session's future requests pick a live one
        self._sessions = {k: v for k, v in self._sessions.items()
                          if v != i}

    def drain_replica(self, name: str, cancel_queued: bool = False):
        """Gracefully remove one replica from rotation: REQUEUE its
        waiting requests to the rest of the fleet first, THEN drain its
        in-flight work. Ordering is the deadlock fix — draining first
        would run the engine until idle while the router still holds
        affinity-pinned work for it (work that can never run: a draining
        replica is excluded from dispatch)."""
        idx = [i for i, r in enumerate(self.replicas) if r.name == name]
        if not idx:
            raise ValueError(f"no replica named {name!r}")
        i = idx[0]
        rep = self.replicas[i]
        rep.draining = True
        # unpin BEFORE requeue/drain so rebalanced + future session
        # requests choose among the remaining replicas
        self._sessions = {k: v for k, v in self._sessions.items()
                          if v != i}
        eng = rep.engine
        for q in list(eng.queue):            # waiting for admission there
            req = eng.release_request(q.req_id)
            if req is not None:
                self._where.pop(req.req_id, None)
                self._queue.append(req)
                self.stats["requeues"] += 1
                _R_REQUEUES.inc(replica=rep.name, why="drain")
                FLIGHT.record("router.requeue", rid=req.req_id,
                              replica=rep.name, why="drain")
                REQUESTS.event(req, "requeued", replica=rep.name,
                               why="drain")
        if rep.role == "prefill":
            # a prefill-only engine never finishes active slots by
            # itself — drive the extract/install loop until it empties
            # instead of engine.drain()'s spin-forever
            eng._draining = True
            while eng.has_work():
                eng.step()
                self._collect_prefilled()
                self._flush_pending()
        else:
            eng.drain(cancel_queued=cancel_queued)
        for rid in eng.pop_finished():
            self._forget(rid)
        self._flush_queue()

    # ------------------------------------------------------------ stepping
    def step(self):
        """One router round: death checks, dispatch, one engine tick per
        live replica with work, then (disaggregated) the extract/install
        handoff. Returns the concatenated [(req_id, token), ...]."""
        for i, rep in enumerate(self.replicas):
            if not rep.alive:
                continue
            try:
                fault_point("router.replica_death", router=self,
                            replica=rep.name)
            except Exception as e:
                self._replica_death(i, e)
        self._flush_queue()
        emitted = []
        for rep in self.replicas:
            if rep.alive and rep.engine.has_work():
                emitted += rep.engine.step()
        if self.disagg:
            self._collect_prefilled()
            self._flush_pending()
        # session durability: capture AFTER the engine ticks, so each
        # snapshot carries this step's freshly generated tokens
        if self.snapshot_every:
            self._step_i += 1
            if self._step_i % self.snapshot_every == 0:
                self._snapshot_sessions()
        for rep in self.replicas:
            if rep.alive:
                for rid in rep.engine.pop_finished():
                    self._forget(rid)
        self._refresh_gauges()
        return emitted

    def _snapshot_sessions(self):
        """Refresh the per-request durability snapshots for everything
        in flight on a live replica. A failed capture (the
        ``serving.snapshot`` chaos site) keeps the previous, staler
        snapshot — restore then just replays a longer tail."""
        for rid, i in list(self._where.items()):
            rep = self.replicas[i]
            if not rep.alive:
                continue
            try:
                snap = rep.engine.snapshot_session(rid)
            except Exception as e:
                FLIGHT.record("serving.snapshot_skipped", rid=rid,
                              replica=rep.name,
                              error=f"{type(e).__name__}: {e}")
                continue
            if snap is not None:
                self._snapshots[rid] = snap

    def _progress_key(self):
        toks = sum(len(r.tokens) for r in self.requests.values())
        done = sum(1 for r in self.requests.values() if r.done)
        pre = sum(c for rep in self.replicas
                  for (_, c) in rep.engine.prefilling.values())
        beams = sum(g.i for rep in self.replicas
                    for g in rep.engine.groups.values())
        return (toks, done, pre, beams, len(self._queue),
                len(self._pending))

    def run(self, parallel: bool = False) -> dict:
        """Drain everything; returns {req_id: token list}. ``parallel``
        free-runs one driver thread per replica (pure replicated
        scale-out — the throughput mode); disaggregation needs the
        orchestrated sequential rounds and ignores the flag."""
        if parallel and not self.disagg:
            self._run_parallel()
        stall = 0
        last = self._progress_key()
        while self.has_work():
            self.step()
            key = self._progress_key()
            stall = stall + 1 if key == last else 0
            last = key
            if stall > 200:
                raise RuntimeError(
                    "router stalled: work remains but no replica can "
                    f"make progress (queue={len(self._queue)}, "
                    f"pending={len(self._pending)})")
        return {rid: r.tokens for rid, r in self.requests.items()}

    def _run_parallel(self):
        """Throughput mode: dispatch everything, then let each replica's
        engine free-run on its own thread — on CPU the jitted tick
        releases the GIL, so N replicas genuinely overlap. Threads are
        joined before returning (nothing outlives the call)."""
        self._flush_queue()
        reps = [r for r in self.replicas if r.alive and r.engine.has_work()]
        if len(reps) < 2:
            return
        errs = []

        def drive(rep):
            try:
                while rep.engine.has_work():
                    rep.engine.step()
            except Exception as e:       # pragma: no cover - surfaced below
                errs.append((rep.name, e))

        threads = [threading.Thread(target=drive, args=(r,),
                                    name=f"pt-router-{r.name}", daemon=True)
                   for r in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            name, e = errs[0]
            raise RuntimeError(f"replica {name} failed: {e}") from e

    def drain(self, cancel_queued: bool = False) -> dict:
        """Fleet-wide graceful shutdown: stop admitting, finish (or
        cancel) everything, return {req_id: tokens}."""
        for rep in self.replicas:
            rep.draining = True
            rep.engine._draining = True
        if cancel_queued:
            for req in list(self._queue):
                self.cancel(req.req_id)
            for rep in self.replicas:
                if rep.alive:
                    for q in list(rep.engine.queue):
                        rep.engine.cancel(q.req_id)
        # draining replicas still FINISH in-flight work; the sequential
        # loop also flushes disaggregated handoffs
        stall = 0
        last = self._progress_key()
        while self.has_work():
            emitted = self._drain_step()
            key = self._progress_key()
            stall = stall + 1 if key == last and not emitted else 0
            last = key
            if stall > 200:
                raise RuntimeError("router drain stalled")
        return {rid: r.tokens for rid, r in self.requests.items()}

    def _drain_step(self):
        emitted = []
        for rep in self.replicas:
            if rep.alive and rep.engine.has_work():
                emitted += rep.engine.step()
        if self.disagg:
            self._collect_prefilled()
            self._flush_pending()
        self._refresh_gauges()
        return emitted

    def assert_quiescent(self):
        """Fleet-wide leak check: the router holds nothing, and every
        replica's pool (dead ones included — their blocks were pulled
        back on death) is fully free."""
        assert not self._queue, f"router queue not empty: {len(self._queue)}"
        assert not self._pending, (
            f"undelivered KV payloads: {len(self._pending)}")
        for rep in self.replicas:
            rep.engine.kv.assert_quiescent()

    def _refresh_gauges(self):
        for rep in self.replicas:
            _R_OUTSTANDING.set(
                rep.engine.outstanding() if rep.alive else 0,
                replica=rep.name)
            _R_HEALTH.set(_VERDICT_NUM[rep.verdict()], replica=rep.name)
        tr, hd = self.stats["transfers"], self.stats["hedges"]
        _R_HEDGE_RATE.set(hd / tr if tr else 0.0)
        if self.degrade is not None:
            self.degrade.poll()
        if self.slo is not None:
            self.slo.poll()
