"""Scheduler: admission, deadlines, preemption policy, backpressure.

The policy layer of the decomposed engine (ISSUE 7). It owns the FCFS
queue, the request registry, intake backpressure (bounded queue +
drain flag), wall-clock deadlines, and the preemption victim policy.
It mutates slot/ledger state only through the orchestrating
:class:`~paddle_tpu.serving.engine.LLMEngine` (``eng``) passed into the
policy methods — the device cache never appears here.
"""
from __future__ import annotations

import itertools
import time
from collections import deque

import numpy as np

from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.goodput import GOODPUT
from paddle_tpu.observability.requests import REQUESTS
from paddle_tpu.serving.telemetry import (_ADAPTER_DEFERRALS, _ADMITTED,
                                          _DEGRADE_SHED, _PREEMPTED,
                                          _QUEUE_WAIT, _REJECTED,
                                          _TENANT_ADMITTED,
                                          _TENANT_QUEUE_WAIT,
                                          _TENANT_THROTTLED, _TENANT_WASTE,
                                          tenant_label)
from paddle_tpu.serving.types import (EngineDrainingError, QueueFullError,
                                      Request)


class Scheduler:
    """FCFS admission queue + deadline/preemption/backpressure policy."""

    def __init__(self, max_queue_len=None, clock=None):
        self.queue: deque[Request] = deque()
        self.requests: dict[int, Request] = {}
        self._ids = itertools.count()
        # robustness: bounded admission queue (None = unbounded), a
        # swappable clock (tests drive deadlines deterministically), and
        # the drain flag (graceful shutdown: finish in-flight, admit
        # nothing new)
        self.max_queue_len = max_queue_len
        self.clock = clock if clock is not None else time.monotonic
        self.draining = False
        self.has_deadlines = False
        # fair multi-tenant admission (ISSUE 14): deficit accounting —
        # each admission charges its tenant prompt+budget tokens, and
        # the pick favours the queued tenant with the smallest
        # charged/weight ratio. Empty while no request carries a
        # tenant_id, in which case admission is EXACTLY the legacy FCFS.
        self.tenant_weights: dict = {}       # tenant -> share weight (1.0)
        self.tenant_charged: dict = {}       # tenant -> tokens charged
        # graceful degradation (ISSUE 16): tenant service class — the
        # ladder's L3 rung sheds (defers, never cancels) "best_effort"
        # tenants at admission; everyone defaults to "standard"
        self.tenant_priority: dict = {}      # tenant -> service class
        # per-tenant token-bucket rate limits (max_tokens_per_s): a
        # tenant with an empty bucket is skipped by the fair pick until
        # refill. Admission debits the same prompt+budget cost the
        # deficit charge uses, and the bucket may go negative — so one
        # large request eventually passes instead of starving forever,
        # and the long-run rate still holds.
        self.tenant_rate: dict = {}          # tenant -> (rate/s, burst)
        self.tenant_bucket: dict = {}        # tenant -> [tokens, last_t]

    def set_tenant_weight(self, tenant, weight: float):
        """Relative admission share for a tenant (default 1.0). A tenant
        with weight 2 is charged half as fast, so it wins the fair pick
        twice as often under contention."""
        if weight <= 0:
            raise ValueError("tenant weight must be positive")
        self.tenant_weights[tenant] = float(weight)

    def set_tenant_priority(self, tenant, priority: str):
        """Service class: "standard" (default) or "best_effort" — the
        degradation ladder sheds best-effort admissions at L3+."""
        if priority not in ("standard", "best_effort"):
            raise ValueError(f"priority must be 'standard' or "
                             f"'best_effort', got {priority!r}")
        self.tenant_priority[tenant] = priority

    def set_tenant_rate(self, tenant, max_tokens_per_s, burst=None):
        """Token-bucket rate limit for one tenant (None removes it).
        ``burst`` is the bucket capacity — the tokens a cold tenant may
        consume instantly — and defaults to one second's worth."""
        if max_tokens_per_s is None:
            self.tenant_rate.pop(tenant, None)
            self.tenant_bucket.pop(tenant, None)
            return
        if max_tokens_per_s <= 0:
            raise ValueError("max_tokens_per_s must be positive")
        burst = float(max_tokens_per_s if burst is None else burst)
        if burst <= 0:
            raise ValueError("burst must be positive")
        self.tenant_rate[tenant] = (float(max_tokens_per_s), burst)
        self.tenant_bucket[tenant] = [burst, self.clock()]

    def _bucket_level(self, tenant, now) -> float:
        """Refill the tenant's bucket up to ``now`` and return its level
        (scheduler clock, so rate tests drive a fake clock)."""
        rate, burst = self.tenant_rate[tenant]
        b = self.tenant_bucket.setdefault(tenant, [burst, now])
        b[0] = min(burst, b[0] + max(0.0, now - b[1]) * rate)
        b[1] = now
        return b[0]

    # ------------------------------------------------------------- intake
    def check_backpressure(self, stats: dict):
        """Reject-on-full/reject-while-draining intake gates — push the
        load signal to the caller instead of buffering unboundedly."""
        if self.draining:
            stats["rejected"] += 1
            _REJECTED.inc(reason="draining")
            raise EngineDrainingError(
                "engine is draining — finishing in-flight requests, "
                "admitting nothing new")
        if (self.max_queue_len is not None
                and len(self.queue) >= self.max_queue_len):
            stats["rejected"] += 1
            _REJECTED.inc(reason="queue_full")
            raise QueueFullError(
                f"admission queue full ({self.max_queue_len} waiting) — "
                "shed load or retry later")

    def enqueue(self, req: Request) -> int:
        """Assign/validate the request id, stamp the submit time, and
        append to the FCFS queue."""
        if req.req_id is None:
            req.req_id = next(self._ids)
        else:
            if req.req_id in self.requests:
                # a duplicate id would alias the BlockManager table AND
                # the reservation ledger of the in-flight request
                raise ValueError(f"req_id {req.req_id} already exists")
            # keep auto ids from ever colliding with explicit ones
            self._ids = itertools.count(
                max(req.req_id + 1, next(self._ids)))
        req._submit_t = self.clock()
        if req.deadline_s is not None or req.max_queue_s is not None:
            self.has_deadlines = True
        self.requests[req.req_id] = req
        self.queue.append(req)
        return req.req_id

    def adopt(self, req: Request) -> int:
        """Register an already-prefilled request WITHOUT queueing it —
        the disaggregated install path (router KV handoff)."""
        if req.req_id is None or req.req_id in self.requests:
            raise ValueError(f"install needs a fresh explicit req_id, "
                             f"got {req.req_id!r}")
        if req.deadline_s is not None or req.max_queue_s is not None:
            self.has_deadlines = True
        self.requests[req.req_id] = req
        return req.req_id

    def pop_finished(self) -> dict:
        done = {rid: r for rid, r in self.requests.items() if r.done}
        for rid in done:
            del self.requests[rid]
        return done

    def release(self, rid: int) -> Request:
        """Forget a request without finishing it (router pull-back)."""
        return self.requests.pop(rid, None)

    # ---------------------------------------------------------- deadlines
    def expire(self, cancel):
        """Finish requests whose wall-clock budget ran out: absolute
        ``deadline_s`` for everyone, ``max_queue_s`` additionally for
        requests still waiting for admission. Runs at the top of every
        tick — an expired request frees its slot/blocks THIS tick, so
        deadlines double as livelock bounds."""
        if not self.has_deadlines or not self.requests:
            return
        now = self.clock()
        queued = {r.req_id for r in self.queue}
        for rid, r in list(self.requests.items()):
            if r.done or r._submit_t is None:
                continue
            age = now - r._submit_t
            if ((r.deadline_s is not None and age >= r.deadline_s)
                    or (rid in queued and r.max_queue_s is not None
                        and age >= r.max_queue_s)):
                cancel(rid, reason="timeout")

    # ---------------------------------------------------------- admission
    def _prefix_lookup(self, eng, req):
        """Memoized prefix-cache probe: ``match_prefix`` hashes/walks the
        whole prompt, and a request stuck at the queue head is re-probed
        every admission attempt — quadratic host work under a deep queue.
        The memo keys on the manager's ``cache_epoch`` (bumped on every
        eviction and commit) plus the effective prompt length (a resume
        changes it), so a stale match is impossible."""
        kv = eng.kv
        p = eng._pr(req)
        epoch = getattr(kv.mgr, "cache_epoch", None)
        memo = req._match_memo
        if (memo is not None and epoch is not None
                and memo[0] == epoch and memo[1] == len(p)):
            return memo[2]
        m = kv.mgr.match_prefix(p, adapter=req.adapter_id)
        if epoch is not None:
            req._match_memo = (epoch, len(p), m)
        return m

    def _pick_index(self, skip=frozenset()):
        """Queue index of the next admission candidate, or None when
        every queued tenant is in ``skip`` (shed or throttled). Pure
        FCFS (the head) while no queued request carries a tenant_id and
        nothing is skipped — the legacy ordering, byte-for-byte.
        Otherwise: token-budget-weighted fair pick — the queued tenant
        with the smallest charged/weight deficit wins, FIFO within the
        tenant. Starvation-free: every admission charges the winner, so
        a saturating tenant's deficit climbs past any light tenant's
        after finitely many admissions. A tenant first seen mid-flight
        starts at the current MINIMUM charge (no retroactive credit for
        time away)."""
        if not skip and all(r.tenant_id is None for r in self.queue):
            return 0
        floor = min(self.tenant_charged.values(), default=0.0)
        best_qi, best_key = None, None
        seen = set()
        for qi, r in enumerate(self.queue):
            t = r.tenant_id
            if t in seen:
                continue                   # FIFO within a tenant
            seen.add(t)
            if t is not None and t in skip:
                continue                   # shed/throttled this pass
            w = self.tenant_weights.get(t, 1.0)
            key = self.tenant_charged.setdefault(t, floor) / w
            if best_key is None or key < best_key:
                best_qi, best_key = qi, key
        return best_qi

    def _charge_tenant(self, req, p):
        """Deficit charge at admission: prompt + remaining budget — the
        worst-case token footprint this admission can consume. Replays
        charge again: a preempted request's re-admission consumes real
        capacity a second time."""
        t = req.tenant_id
        if t is None:
            return
        floor = min(self.tenant_charged.values(), default=0.0)
        gen = max(0, req.max_new_tokens - len(req.tokens))
        cost = len(p) + gen
        self.tenant_charged[t] = self.tenant_charged.get(t, floor) + cost
        if t in self.tenant_rate:
            # debit the rate bucket with the same worst-case cost; it
            # may go negative, which is what lets one oversized request
            # through and then makes the tenant wait out the overdraft
            b = self.tenant_bucket.setdefault(
                t, [self.tenant_rate[t][1], self.clock()])
            b[0] -= cost

    def _admission_skips(self, eng, counted: set) -> frozenset:
        """Tenants excluded from the current admission pass: best-effort
        tenants while the degradation ladder holds L3+, and tenants
        whose token bucket ran dry. Skipped requests stay queued — both
        mechanisms defer, never drop. ``counted`` dedupes the skip
        metrics to once per tenant per ``select_admissions`` call."""
        deg = getattr(eng, "degrade", None)
        shed = deg is not None and deg.shed_best_effort()
        if not shed and not self.tenant_rate:
            return frozenset()
        now = self.clock()
        skip = set()
        for t in {r.tenant_id for r in self.queue if r.tenant_id is not None}:
            if shed and self.tenant_priority.get(t) == "best_effort":
                skip.add(t)
                if ("shed", t) not in counted:
                    counted.add(("shed", t))
                    _DEGRADE_SHED.inc(tenant=tenant_label(t))
            elif t in self.tenant_rate and self._bucket_level(t, now) <= 0.0:
                skip.add(t)
                if ("throttle", t) not in counted:
                    counted.add(("throttle", t))
                    _TENANT_THROTTLED.inc(tenant=tenant_label(t))
        return frozenset(skip)

    def select_admissions(self, eng):
        """Move queued requests into free slots while the pool can cover
        their worst case; returns (greedy (slot, req) pairs, beam (slots,
        req) pairs). A beam request needs num_beams slots. Candidate
        order is ``_pick_index`` — legacy FCFS without tenants, weighted
        fair share with them; a blocked candidate stops admission for the
        tick (capacity pressure must not starve the fair winner)."""
        # drain-before-admit seam (ISSUE 20): admission mutates slot
        # state and block tables the async pipeline's in-flight ticks
        # already captured — the engine must land every dispatched tick
        # before the scheduler touches a slot
        assert not getattr(eng, "_async_win", None), \
            "admission with dispatched-but-undrained async ticks in flight"
        kv = eng.kv
        free_slots = list(np.nonzero(eng.slot_req < 0)[0])
        admits, beam_admits = [], []
        skip_counted: set = set()
        while self.queue and free_slots:
            # recomputed every iteration: an admission can drain its
            # tenant's rate bucket mid-pass
            skips = self._admission_skips(eng, skip_counted)
            qi = self._pick_index(skips)
            if qi is None:
                break                      # everyone queued is deferred
            req = self.queue[qi]
            k = req.num_beams
            p = eng._pr(req)
            # prefix-cache lookup BEFORE the capacity gate: shared blocks
            # cost nothing, so a mostly-cached prompt admits under
            # pressure an uncached one would wait out
            cached = (self._prefix_lookup(eng, req)
                      if eng.prefix_caching and k == 1 else None)
            n_shared = len(cached) if cached else 0
            # the TOKEN frontier: the radix trie reports partial-block
            # hits (match.token_count), the flat manager whole blocks
            ct = (getattr(cached, "token_count",
                          n_shared * eng.block_size) if cached else 0)
            if eng.preemption and k == 1:
                # optimistic: cover only the first prefill chunk (+1
                # decode-headroom block); out-of-blocks later preempts.
                # Only the FULLY shared blocks are free — a partial COW
                # hit allocates its private boundary block out of `need`
                need = (kv.blocks_needed(
                    min(len(p), ct + eng.max_prompt_len)) - n_shared + 1)
            else:
                need = eng._worst_case_blocks(req)
            if (k > len(free_slots)
                    or need > kv.free_blocks - kv.reserved):
                # stall forensics: which ledger state holds the blocks
                # (or slots) the queue head is waiting on
                kv.record_stall(need, slots_short=(k > len(free_slots)))
                break                      # do not starve the fair winner
            if req.adapter_id is not None and eng._multilora_on():
                # make the adapter device-resident and PIN it before the
                # request can touch a slot. Failure (cache fully pinned,
                # or an injected serving.adapter_swap fault) defers the
                # admission — the request stays queued, retried next tick,
                # and nothing was mutated (the fault site fires
                # pre-upload; acquire is exception-atomic)
                try:
                    eng.adapter_store.acquire(req.adapter_id)
                except Exception as e:
                    _ADAPTER_DEFERRALS.inc()
                    FLIGHT.record("serving.adapter_defer",
                                  rid=req.req_id,
                                  adapter=str(req.adapter_id),
                                  err=f"{type(e).__name__}: {e}")
                    break
                eng._adapter_pins[req.req_id] = req.adapter_id
            del self.queue[qi]
            req._match_memo = None
            req._adopted = ct if k == 1 else 0
            _ADMITTED.inc()
            self._charge_tenant(req, p)
            wait = (max(0.0, self.clock() - req._submit_t)
                    if req._submit_t is not None else None)
            if wait is not None:
                _QUEUE_WAIT.observe(wait)
            if req.tenant_id is not None:
                _TENANT_ADMITTED.inc(tenant=tenant_label(req.tenant_id))
                if wait is not None:
                    _TENANT_QUEUE_WAIT.observe(
                        wait, tenant=tenant_label(req.tenant_id))
            # token-level hit accounting: every cached token is prefill
            # device work the pool did NOT have to repeat
            GOODPUT.saved(ct, tenant=req.tenant_id)
            if req._resume is not None:
                # replayed after preemption: every resume token past the
                # prefix-cache hit is device work already paid for once
                GOODPUT.waste("replay_prefill", max(0, len(p) - ct),
                              tenant=req.tenant_id)
                if req.tenant_id is not None:
                    _TENANT_WASTE.inc(max(0, len(p) - ct),
                                      tenant=tenant_label(req.tenant_id),
                                      why="replay_prefill")
                REQUESTS.event(req, "replayed",
                               replica=getattr(eng, "trace_name", None),
                               resume_tokens=len(p), cached_tokens=ct)
            REQUESTS.event(req, "admitted",
                           replica=getattr(eng, "trace_name", None),
                           cached_tokens=ct)
            if eng.preemption and k == 1:
                need = 0                   # no standing reservation
            kv.begin(req.req_id, need)
            if k == 1:
                slot = int(free_slots.pop(0))
                if cached:
                    kv.mgr.adopt_prefix(req.req_id, cached)
                if cached or len(p) > eng.max_prompt_len:
                    # chunk-prefill path from offset ct: claims the slot
                    # INACTIVE; blocks allocate chunk-by-chunk against
                    # the reservation. (Cached short prompts ride it too —
                    # the chunk program is the one that prefills from an
                    # arbitrary offset over the slot's pool prefix.)
                    kv.hold(req.req_id, need)
                    eng.slot_req[slot] = req.req_id
                    # admission recency stamped at slot-claim: preemption
                    # victim selection keys on THIS, not on req_id (user
                    # ids need not be monotonic with admission)
                    eng._adm_counter += 1
                    eng.adm_order[slot] = eng._adm_counter
                    eng.prefilling[req.req_id] = (slot, ct)
                    continue
                kv.allocate(req.req_id, len(p))
                if eng.prefix_caching:
                    kv.mgr.commit_prefix(req.req_id, p,
                                          adapter=req.adapter_id)
                kv.update(req.req_id)
                admits.append((slot, req))
            else:
                slots = [int(free_slots.pop(0)) for _ in range(k)]
                # full worst-case reservation up front; relaxed to
                # (need - live) as the group's blocks materialise
                kv.hold(req.req_id, need)
                beam_admits.append((slots, req))
        return admits, beam_admits

    # --------------------------------------------------------- preemption
    @staticmethod
    def _protect(protect_rid):
        """Normalise the protect argument to a set of req_ids (a single
        rid, an iterable of rids, or None)."""
        if protect_rid is None:
            return frozenset()
        if isinstance(protect_rid, (set, frozenset, list, tuple)):
            return frozenset(protect_rid)
        return frozenset((protect_rid,))

    def preempt(self, eng, protect_rid=None) -> bool:
        """Evict the YOUNGEST active greedy request (LIFO — vLLM's policy:
        the oldest in-flight work is closest to completion) to free its
        blocks. The victim re-queues at the queue head with resume-prompt
        = prompt + generated-so-far; on re-admission the resume prefill
        recomputes its KV (prefix-cache hits cover whatever of its old
        blocks survived). When no active slot qualifies, falls back to
        evicting a CHUNK-PREFILLING request (slot inactive, blocks held):
        without this, two long prompts mid-prefill on a dry pool would
        spin forever — neither active nor evictable. Returns False when
        nothing is preemptible."""
        protect = self._protect(protect_rid)
        cand = [int(s) for s in np.nonzero(eng.active & ~eng.is_beam)[0]
                if int(eng.slot_req[s]) not in protect]
        if self.preempt_from(eng, cand):
            return True
        return self.preempt_prefilling(eng, protect_rid)

    def preempt_prefilling(self, eng, protect_rid=None) -> bool:
        """Evict the youngest in-flight chunked prefill — youngest by
        ADMISSION order (``adm_order`` stamped at slot-claim), not by
        req_id: ids may be user-supplied and non-monotonic, and evicting
        an explicitly-numbered old request as if youngest would churn the
        work closest to completion. Free its blocks and re-queue it at
        the head; consumed chunks are recomputed on re-admission —
        prefill is deterministic, so this only costs work, never
        correctness. Rows already STAGED into this tick's chunk batch must
        ride in ``protect_rid`` — the jitted scatter would otherwise write
        their KV into blocks just handed to someone else."""
        protect = self._protect(protect_rid)
        cand = [rid for rid in eng.prefilling if rid not in protect]
        if not cand:
            return False
        rid = max(cand, key=lambda r: eng.adm_order[eng.prefilling[r][0]])
        slot, consumed = eng.prefilling.pop(rid)
        req = self.requests[rid]
        if eng.prefix_caching and consumed:
            # the chunks already scattered are finished device work —
            # commit them so the replay prefill re-matches instead of
            # recomputing (replay_prefill waste shrinks to the tail)
            eng.kv.mgr.commit_prefix(rid, eng._pr(req)[:consumed],
                                     adapter=req.adapter_id)
        eng.kv.free(rid)
        eng.kv.release(rid)
        eng._release_adapter(rid)
        eng.slot_req[slot] = -1
        self.queue.appendleft(req)
        eng.stats["preemptions"] += 1
        _PREEMPTED.inc()
        FLIGHT.record("serving.preempt", rid=rid, slot=int(slot),
                      phase="prefill")
        REQUESTS.event(req, "preempted",
                       replica=getattr(eng, "trace_name", None),
                       phase="prefill")
        return True

    def preempt_from(self, eng, cand) -> bool:
        if eng.window is not None or eng._dyn_rope:
            # the resume prefill rides the chunk path, which refuses
            # window-recycling and dynamic-NTK for long prompts — only
            # slots whose resume form fits one plain prefill qualify
            cand = [s for s in cand
                    if len(self.requests[int(eng.slot_req[s])].prompt)
                    + len(self.requests[int(eng.slot_req[s])].tokens)
                    <= eng.max_prompt_len]
        if not cand:
            return False
        slot = max(cand, key=lambda s: eng.adm_order[s])
        rid = int(eng.slot_req[slot])
        req = self.requests[rid]
        req._resume = (np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
            if req.tokens else req.prompt)
        if eng.prefix_caching:
            # park everything the victim computed — full blocks AND (in
            # the radix trie) the partial frontier block — so the resume
            # prefill starts at the token frontier, not from scratch.
            # ``cur`` is the cache frontier: the newest sampled token's
            # KV is not scattered yet, so it must not be committed
            eng.kv.mgr.commit_prefix(
                rid, req._resume[:min(len(req._resume),
                                      int(eng.cur[slot]))],
                adapter=req.adapter_id)
        eng.kv.free(rid)
        eng.kv.release(rid)
        eng._release_adapter(rid)
        eng.active[slot] = False
        eng.slot_req[slot] = -1
        eng.draft_cur[slot] = 0     # draft cache freed with the slot
        self.queue.appendleft(req)
        eng.stats["preemptions"] += 1
        _PREEMPTED.inc()
        FLIGHT.record("serving.preempt", rid=rid, slot=int(slot),
                      phase="decode")
        REQUESTS.event(req, "preempted",
                       replica=getattr(eng, "trace_name", None),
                       phase="decode")
        return True
