"""Serving-layer metric instruments (engine + router).

One module so the scheduler, engine, and router share the same
process-global instruments without import cycles. Request-relative
timings (TTFT, inter-token latency, queue wait) use the ENGINE clock —
the swappable ``clock`` ctor arg — so deadline tests driving a fake
clock see deterministic histograms; host work timings (tick, drain) use
the real monotonic clock. A serve loop exports everything with
``paddle_tpu.observability.dump(prefix)``.

Every tenant-labeled write goes through :func:`tenant_label`, the
cardinality guard: past ``PT_TENANT_LABEL_CAP`` distinct tenants the
label collapses to ``__overflow__`` (counted in
``serving_tenant_label_overflow_total``), so a tenant-id-fuzzing client
cannot grow the registry or the Prometheus export without bound.
"""
import os

from paddle_tpu.observability import METRICS

# ------------------------------------------------------------- engine
_ADMITTED = METRICS.counter(
    "serving_admissions_total", "requests admitted into cache slots")
_PREEMPTED = METRICS.counter(
    "serving_preemptions_total", "requests evicted and re-queued")
_TIMEOUTS = METRICS.counter(
    "serving_timeouts_total", "requests expired (deadline_s/max_queue_s)")
_CANCELLED = METRICS.counter(
    "serving_cancellations_total", "requests cancelled by the caller")
_REJECTED = METRICS.counter(
    "serving_rejections_total", "admissions refused at intake",
    labelnames=("reason",))
_TOKENS = METRICS.counter(
    "serving_tokens_total", "tokens sampled and emitted")
_FINISHED = METRICS.counter(
    "serving_finished_total", "requests finished, by finish_reason",
    labelnames=("reason",))
_QUEUE_DEPTH = METRICS.gauge(
    "serving_queue_depth", "requests waiting for admission")
_ACTIVE_SLOTS = METRICS.gauge(
    "serving_active_slots", "cache slots actively decoding")
_KV_IN_USE = METRICS.gauge(
    "serving_kv_blocks_in_use", "paged KV blocks currently allocated")
_KV_UTIL = METRICS.gauge(
    "serving_kv_block_utilization", "allocated fraction of the KV pool")
_TTFT = METRICS.histogram(
    "serving_ttft_seconds", "submission → first token (engine clock)")
_TOK_LAT = METRICS.histogram(
    "serving_token_latency_seconds", "inter-token gap (engine clock)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
_QUEUE_WAIT = METRICS.histogram(
    "serving_queue_wait_seconds", "submission → admission (engine clock)")
_TICK = METRICS.histogram(
    "serving_tick_seconds", "wall time of one engine tick")
# decode-tick anatomy (ISSUE 12): every tick observes all five phases
# (zero seconds included), so per phase count == tick count and the five
# observations of a tick sum to that tick's serving_tick_seconds
# observation by construction — host is defined as the remainder
_TICK_BREAKDOWN = METRICS.histogram(
    "serving_tick_breakdown_seconds",
    "per-tick wall time by phase: prefill (admission + chunk forwards), "
    "draft, verify, sample (the fused decode forward + token fetch), "
    "host (everything else in the tick)",
    labelnames=("phase",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
_DRAIN = METRICS.histogram(
    "serving_drain_seconds", "wall time of graceful drain",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
# async pipelined decode (ISSUE 20): depth-K deferred-sync decode —
# the depth gauge + hidden histogram ship only when async_depth > 0,
# so depth-0 engines export byte-identical dumps to pre-async runs.
# Under async, the breakdown's `host` phase reports only EXPOSED host
# time; host work performed while dispatched ticks were still in
# flight lands here instead (mirror of the trainer's overlap-aware
# MFU split). One observation per tick, so count == tick count and
# the five-phase sum == serving_tick_seconds contract keeps holding.
_ASYNC_DEPTH = METRICS.gauge(
    "serving_async_depth",
    "configured decode pipeline depth (dispatched-but-unfetched ticks "
    "kept in flight; 0 = fully synchronous)")
_ASYNC_DRAINS = METRICS.counter(
    "serving_async_drains_total",
    "async decode windows drained before a tick the pipeline cannot "
    "cover, by cause (admit, prefill, beam, grammar, adapter, spec, "
    "growth, finish, cancel, exception, boundary)",
    labelnames=("why",))
_TICK_HIDDEN = METRICS.histogram(
    "serving_tick_host_hidden_seconds",
    "per-tick host work (token emission, stream callbacks, finish "
    "bookkeeping) performed while async-dispatched device ticks were "
    "still in flight — hidden time, excluded from the breakdown's "
    "exposed `host` phase",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
# speculative decoding (ISSUE 5): proposal/acceptance accounting plus the
# per-tick commit size — tokens_per_tick > 1 is the whole point
_SPEC_PROPOSED = METRICS.counter(
    "serving_spec_proposed_total", "draft tokens proposed for verification")
_SPEC_ACCEPTED = METRICS.counter(
    "serving_spec_accepted_total", "draft tokens accepted by the target")
_SPEC_FALLBACKS = METRICS.counter(
    "serving_spec_fallbacks_total",
    "spec ticks abandoned before verify (fault injection) — the engine "
    "fell back to the one-token tick")
_SPEC_RATE = METRICS.gauge(
    "serving_spec_acceptance_rate",
    "cumulative accepted/proposed draft-token ratio")
_SPEC_TOKENS = METRICS.histogram(
    "serving_spec_tokens_per_tick",
    "tokens committed per slot per speculative tick",
    buckets=(1, 2, 3, 4, 5, 6, 8, 12, 16))
_SPEC_DRAFT_REUSE = METRICS.counter(
    "serving_spec_draft_reuse_tokens_total",
    "draft-cache positions adopted from a slot's resident draft K/V at "
    "activation (radix prefix hits whose draft-side re-prefill was "
    "skipped entirely)")
# prefix cache: cumulative adopt/evict counts exported from the block
# manager's cache_stats (deltas pushed each gauge refresh), plus the
# lifetime hit rate (blocks adopted / blocks prefill would have written)
_PREFIX_HITS = METRICS.counter(
    "serving_prefix_hit_blocks_total",
    "prompt blocks adopted from the prefix cache instead of prefilled")
_PREFIX_EVICTIONS = METRICS.counter(
    "serving_prefix_evictions_total",
    "parked prefix blocks evicted to satisfy new allocations")
_PREFIX_HIT_RATE = METRICS.gauge(
    "serving_prefix_hit_rate",
    "prefix-cache hit blocks / prompt blocks requested (lifetime)")
# radix trie (ISSUE 10): token-level accounting — the trie matches the
# longest shared token span, so hits are no longer block-quantised; a
# partial hit is a boundary block adopted copy-on-write
_PREFIX_TOKEN_HITS = METRICS.counter(
    "serving_prefix_token_hits_total",
    "prompt tokens served from the prefix cache (full-block shares plus "
    "partial copy-on-write boundary hits) instead of prefilled")
_PREFIX_PARTIAL_HITS = METRICS.counter(
    "serving_prefix_partial_hits_total",
    "partially-filled boundary blocks adopted copy-on-write from the "
    "radix trie")
_PREFIX_TOKEN_HIT_RATE = METRICS.gauge(
    "serving_prefix_token_hit_rate",
    "prefix-cache hit tokens / prompt tokens probed (lifetime)")
# MoE serving: routing choices dropped by expert-capacity overflow
# (always 0 for dropless models — Mixtral/Qwen2-MoE serve with
# capacity_factor=None)
_MOE_DROPPED = METRICS.counter(
    "moe_dropped_tokens_total",
    "MoE routing assignments dropped at expert capacity")

# ---------------------------------------------- multi-tenancy (ISSUE 14)
# per-tenant accounting: the fair scheduler charges token budgets at
# admission and these break the engine's aggregate goodput/waste story
# down by tenant — a saturating tenant's waste must not hide in totals
_TENANT_TOKENS = METRICS.counter(
    "serving_tenant_tokens_total", "tokens emitted, by tenant",
    labelnames=("tenant",))
_TENANT_ADMITTED = METRICS.counter(
    "serving_tenant_admissions_total", "requests admitted, by tenant",
    labelnames=("tenant",))
_TENANT_QUEUE_WAIT = METRICS.histogram(
    "serving_tenant_queue_wait_seconds",
    "submission → admission (engine clock), by tenant",
    labelnames=("tenant",))
_TENANT_WASTE = METRICS.counter(
    "serving_tenant_waste_tokens_total",
    "wasted work, by tenant and cause (replay_prefill, spec_rejected)",
    labelnames=("tenant", "why"))
# per-tenant SLO inputs (ISSUE 19): the SLOTracker computes burn rates
# from windowed deltas of these — latency objectives from the tenant
# histograms, availability from finished{reason} + rejections
_TENANT_TTFT = METRICS.histogram(
    "serving_tenant_ttft_seconds",
    "submission → first token (engine clock), by tenant",
    labelnames=("tenant",))
_TENANT_TOK_LAT = METRICS.histogram(
    "serving_tenant_token_latency_seconds",
    "inter-token gap (engine clock), by tenant",
    labelnames=("tenant",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
_TENANT_FINISHED = METRICS.counter(
    "serving_tenant_finished_total",
    "requests finished, by tenant and finish_reason",
    labelnames=("tenant", "reason"))
_TENANT_REJECTED = METRICS.counter(
    "serving_tenant_rejections_total",
    "admissions refused at intake for requests carrying a tenant_id, "
    "by tenant", labelnames=("tenant",))

# ------------------------------------- tenant label-cardinality guard
_TENANT_OVERFLOW = METRICS.counter(
    "serving_tenant_label_overflow_total",
    "tenant-labeled metric writes collapsed into the __overflow__ label "
    "because the distinct-tenant cap (PT_TENANT_LABEL_CAP) was reached")

TENANT_OVERFLOW_LABEL = "__overflow__"
_tenant_labels_seen: set = set()


def tenant_label(tenant) -> str:
    """The label value for one tenant-labeled metric write. Returns
    ``str(tenant)`` for the first ``PT_TENANT_LABEL_CAP`` (default 64)
    distinct tenants seen by this process, then collapses every new
    tenant id to ``__overflow__`` and counts the collapse — bounding
    registry cardinality against tenant-id fuzzing. The cap is read per
    call so tests (and operators) can change it mid-flight."""
    t = str(tenant)
    if t in _tenant_labels_seen:
        return t
    try:
        cap = int(os.environ.get("PT_TENANT_LABEL_CAP", "64"))
    except ValueError:
        cap = 64
    if len(_tenant_labels_seen) < cap:
        _tenant_labels_seen.add(t)
        return t
    _TENANT_OVERFLOW.inc()
    return TENANT_OVERFLOW_LABEL


def reset_tenant_labels():
    """Forget the seen-tenant set (test hygiene — the conftest registry
    reset calls this so one test's tenants can't exhaust another's cap)."""
    _tenant_labels_seen.clear()
# adapter cache (batched multi-LoRA): device-resident stacked A/B slots
_ADAPTER_UPLOADS = METRICS.counter(
    "serving_adapter_uploads_total",
    "host→device adapter uploads into the stacked LoRA cache")
_ADAPTER_EVICTIONS = METRICS.counter(
    "serving_adapter_evictions_total",
    "resident adapters evicted (LRU) to make room for an upload")
_ADAPTER_HITS = METRICS.counter(
    "serving_adapter_cache_hits_total",
    "adapter lookups served by the device-resident cache")
_ADAPTER_MISSES = METRICS.counter(
    "serving_adapter_cache_misses_total",
    "adapter lookups that required a host→device upload")
_ADAPTER_RESIDENT = METRICS.gauge(
    "serving_adapter_resident", "adapters resident in the device cache")
_ADAPTER_DEFERRALS = METRICS.counter(
    "serving_adapter_admit_deferrals_total",
    "admissions deferred because the adapter could not be made resident "
    "(cache fully pinned, or an injected serving.adapter_swap fault)")
# grammar-constrained decoding: mask bookkeeping
_GRAMMAR_TOKENS = METRICS.counter(
    "serving_grammar_tokens_total",
    "tokens emitted under a grammar mask (all mask-legal by construction)")
_GRAMMAR_SPEC_REJECTS = METRICS.counter(
    "serving_grammar_spec_rejects_total",
    "drafted tokens rejected by the grammar mask before the target "
    "accept rule was consulted")

# ------------------------------------------------------------- router
_R_DISPATCH = METRICS.counter(
    "router_dispatch_total", "requests dispatched to a replica",
    labelnames=("replica",))
_R_REQUEUES = METRICS.counter(
    "router_requeues_total",
    "requests pulled back from a replica and re-dispatched, by replica "
    "and cause (replica_death, kv_transfer, dispatch_fault, drain)",
    labelnames=("replica", "why"))
_R_OUTSTANDING = METRICS.gauge(
    "router_replica_outstanding", "not-yet-finished requests per replica",
    labelnames=("replica",))
_R_HEALTH = METRICS.gauge(
    "router_replica_health",
    "per-replica health verdict (0 OK / 1 WARN / 2 CRIT)",
    labelnames=("replica",))
_R_TRANSFERS = METRICS.counter(
    "router_kv_transfers_total",
    "prefilled sequences shipped prefill→decode (disaggregated mode)")
_R_TRANSFER_BLOCKS = METRICS.counter(
    "router_kv_transfer_blocks_total",
    "KV blocks shipped prefill→decode (disaggregated mode)")
_R_DEATHS = METRICS.counter(
    "router_replica_deaths_total", "replicas declared dead by the router")

# ----------------------------------- graceful degradation (ISSUE 16)
# the reaction layer: ladder rung + transitions, shed/throttle skips,
# session durability, and the hardened KV-handoff transport
_DEGRADE_LEVEL = METRICS.gauge(
    "serving_degrade_level",
    "current degradation-ladder rung: 0 none, 1 spec off, 2 prefill "
    "budget shrunk, 3 best-effort tenants shed, 4 new sessions rejected")
_DEGRADE_TRANSITIONS = METRICS.counter(
    "serving_degrade_transitions_total",
    "degradation-ladder transitions, by direction (up/down) and target "
    "rung", labelnames=("direction", "to"))
_DEGRADE_SHED = METRICS.counter(
    "serving_degrade_shed_total",
    "admission passes that skipped a best-effort tenant while the "
    "ladder held L3+ (requests stay queued and admit on recovery)",
    labelnames=("tenant",))
_TENANT_THROTTLED = METRICS.counter(
    "serving_tenant_throttled_total",
    "admission passes that skipped a tenant whose token bucket was "
    "empty (max_tokens_per_s rate limit), by tenant",
    labelnames=("tenant",))
_SNAPSHOTS = METRICS.counter(
    "serving_session_snapshots_total",
    "host-side session-durability snapshots captured")
_R_RESTORES = METRICS.counter(
    "router_session_restores_total",
    "sessions restored from a snapshot onto a surviving replica after "
    "a repeat replica death (instead of failing with replica_death)")
_R_TRANSFER_RETRIES = METRICS.counter(
    "router_transfer_retries_total",
    "KV-handoff ship attempts retried, by replica and cause (partial = "
    "failed geometry/checksum validation, error = transport exception)",
    labelnames=("replica", "why"))
_R_HEDGES = METRICS.counter(
    "router_hedges_total",
    "KV handoffs re-dispatched to another decode replica after the "
    "primary ship blew its p95-derived deadline (straggler hedging)")
_R_HEDGE_RATE = METRICS.gauge(
    "router_hedge_rate",
    "lifetime hedged / successful KV handoffs — sustained hedging "
    "means a straggling replica or transport link")
_R_TRANSFER_SECONDS = METRICS.histogram(
    "router_kv_transfer_seconds",
    "wall time of one successful KV-handoff delivery (ship + "
    "validation) — feeds the p95-derived hedging deadline",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
