"""Quantized serving subsystem (ISSUE 17).

The engine-facing entry points of the LLM.int8()/SmoothQuant recipe
(PAPERS.md) over the paged serving stack:

* :func:`quantize_for_serving` — structure-agnostic weight-only
  quantization (int8 / packed int4 / GPTQ) of any model the paged
  forwards can drive: Llama/Qwen dense layers ride the existing
  ``QuantizedWeight`` + ``wo_matmul`` dispatch from ``quantization.py``;
  Mixtral/Qwen2-MoE/MoE expert stacks get :class:`QuantizedExpertStack`
  (a 3-D [E, K, N] variant that ``distributed.moe`` dequantizes on the
  fly inside the jitted forward). Honours the ``PT_QUANT_WEIGHTS=0``
  kill switch by returning the model untouched.

* :func:`smooth_for_serving` — SmoothQuant-style per-channel outlier
  migration: activation scale is folded OUT of the RMSNorm weight and
  INTO the adjacent projection (norm/s ↔ W·s), so the product is exact
  while the quantized weight distribution flattens. With ``calib_ids``
  the migration follows measured activation absmax (dense Llama models
  only — the capture forward is structure-specific); without, a
  weight-balancing heuristic that equalises per-in-channel weight
  magnitude. ``o_proj``/``down_proj`` are NOT smoothed: they have no
  preceding norm to fold into (their input is an attention/SiLU
  product), so migration has nowhere to hide the scale.

* quality instrumentation — quantization error is measured, never
  assumed: :func:`quant_quality` reports logit MSE and greedy
  match-rate against a reference model and publishes both as
  ``serving_quant_*`` gauges next to the throughput metrics.

The int8 KV-cache leg lives in ``models/paged.py`` (quantize-on-write /
dequantize-on-read around the block pools — ``PagedKVCache.init(...,
kv_dtype="int8")``, ``PT_QUANT_KV=0`` kill switch) and is wired through
``LLMEngine(kv_dtype="int8")``; this module only hosts the weight side
and the shared quality/capacity instruments.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.paged import _backbone, is_moe_model
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.quantization import (QuantizedWeight, _capture_calib,
                                     quantize_llama_weights, weight_quantize)

__all__ = [
    "QuantizedExpertStack", "expert_stack_quantize", "weights_quant_enabled",
    "quantize_for_serving", "smooth_for_serving", "quant_quality",
    "quantized_weight_bytes",
]

# ---- instruments (published by quantize_for_serving / quant_quality) -------
_Q_BITS = METRICS.gauge(
    "serving_quant_weight_bits",
    "Weight-only quantization bit-width of the last model passed through "
    "quantize_for_serving (0 = unquantized / kill switch active)")
_Q_LAYERS = METRICS.gauge(
    "serving_quant_layers",
    "Decoder layers whose projections were converted to quantized weights "
    "by the last quantize_for_serving call")
_Q_WEIGHT_BYTES = METRICS.gauge(
    "serving_quant_weight_bytes",
    "HBM bytes of the quantized projection/head weights (codes + scales) "
    "after the last quantize_for_serving call")
_Q_SMOOTHED = METRICS.gauge(
    "serving_quant_smoothed",
    "1 when SmoothQuant-style activation smoothing was folded into the "
    "weights before quantization, else 0")
_Q_MSE = METRICS.gauge(
    "serving_quant_logit_mse",
    "Mean squared error between reference and quantized logits from the "
    "last quant_quality probe")
_Q_MATCH = METRICS.gauge(
    "serving_quant_greedy_match_rate",
    "Fraction of positions whose argmax token matches the reference in "
    "the last quant_quality probe")


def weights_quant_enabled() -> bool:
    """``PT_QUANT_WEIGHTS=0`` kill switch. Checked when a model is
    quantized (``quantize_for_serving`` becomes the identity), NOT per
    trace — an already-quantized model keeps serving; rebuild from the
    bf16 checkpoint to actually revert."""
    return os.environ.get("PT_QUANT_WEIGHTS", "1").strip().lower() \
        not in ("0", "off")


# ---- 3-D expert stacks ------------------------------------------------------

class QuantizedExpertStack:
    """int8/int4 expert weight stack + per-(expert, out-channel) scale.

    The MoE analogue of :class:`~paddle_tpu.quantization.QuantizedWeight`:
    original stack [E, K, N] (expert, in, out). int8 stores codes as
    [E, K, N] int8; int4 packs two 4-bit values per byte along K ->
    [E, ceil(K/2), N] (low nibble = even k). ``distributed.moe`` detects
    the ``dequantize`` attribute and rebuilds the compute-dtype stack on
    the fly inside the jitted forward, so HBM holds 1 (or 0.5)
    byte/param for the dominant expert weights.
    """

    def __init__(self, q, scale, bits: int, k: int):
        self.q = q
        self.scale = scale          # [E, 1, N] fp32
        self.bits = int(bits)
        self.k = int(k)

    def tree_flatten(self):
        return (self.q, self.scale), (self.bits, self.k)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0], aux[1])

    @property
    def shape(self):
        return (self.q.shape[0], self.k, self.q.shape[-1])

    def nbytes(self):
        return self.q.size * self.q.dtype.itemsize + self.scale.size * 4

    def unpack(self):
        """int8 [E, K, N] values (sign-extended nibbles for int4)."""
        if self.bits == 8:
            return self.q
        packed = self.q
        low = jnp.right_shift(jnp.left_shift(packed, 4), 4)  # sign-extends
        high = jnp.right_shift(packed, 4)
        e, _, n = packed.shape
        out = jnp.stack([low, high], axis=2).reshape(e, -1, n)
        return out[:, : self.k]

    def dequantize(self, dtype=jnp.float32):
        return (self.unpack().astype(jnp.float32) * self.scale).astype(dtype)


jax.tree_util.register_pytree_node(
    QuantizedExpertStack,
    lambda t: t.tree_flatten(),
    QuantizedExpertStack.tree_unflatten)


def expert_stack_quantize(w, algo: str = "weight_only_int8"):
    """RTN per-(expert, out-channel) symmetric quantization of a
    [E, K, N] expert stack."""
    bits = {"weight_only_int8": 8, "weight_only_int4": 4}[algo]
    e, k, n = w.shape
    qmax = 2.0 ** (bits - 1) - 1
    f = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=1, keepdims=True),
                        1e-8) / qmax
    q = jnp.clip(jnp.round(f / scale), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        if k % 2:
            q = jnp.concatenate(
                [q, jnp.zeros((e, 1, n), q.dtype)], axis=1)
        low = q[:, 0::2]
        high = q[:, 1::2]
        q = ((high.astype(jnp.int32) << 4)
             | (low.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    return QuantizedExpertStack(q, scale, bits, k)


# ---- SmoothQuant-style activation smoothing ---------------------------------

def _fold(norm, s, *targets):
    """Exact migration norm/s ↔ W·s: the norm output shrinks by s per
    channel and every consumer of that output grows its matching input
    rows by s, so each product is unchanged (up to f32 rounding)."""
    w = norm.weight
    norm.weight = (w.astype(jnp.float32) / s).astype(w.dtype)
    out = []
    for t in targets:
        if t is None:
            out.append(None)
        elif t.ndim == 3:       # [E, K, N] expert stack
            out.append((t.astype(jnp.float32) * s[None, :, None])
                       .astype(t.dtype))
        else:                   # [K, N] projection (or [K, E] router)
            out.append((t.astype(jnp.float32) * s[:, None]).astype(t.dtype))
    return out


def _smooth_scale(a_x, w, alpha):
    """s = a_x^alpha / a_w^(1-alpha) per in-channel, clipped to keep the
    fold numerically sane. ``w``: 2-D [K, N] or 3-D [E, K, N]."""
    f = jnp.abs(w.astype(jnp.float32))
    red = (0, 2) if f.ndim == 3 else (1,)
    a_w = jnp.maximum(jnp.max(f, axis=red), 1e-8)
    s = (a_x ** alpha) / (a_w ** (1.0 - alpha))
    return jnp.clip(s, 1e-3, 1e3)


def smooth_for_serving(model, *, calib_ids=None, alpha: float = 0.5):
    """Fold SmoothQuant-style per-channel smoothing into the weights
    IN PLACE (call BEFORE :func:`quantize_for_serving`; the bf16 model
    computes the same function modulo float rounding).

    Two foldable seams per decoder layer:
      input_layernorm          ↔ qkv_proj
      post_attention_layernorm ↔ gate_up (dense MLP, every MoE expert,
                                 AND the router gate — all consume the
                                 same normed activations)

    ``calib_ids`` [B, S] drives measured activation absmax (dense
    Llama-family only); None uses a_x = 1, i.e. pure weight-magnitude
    balancing, valid for every structure.
    """
    bb = _backbone(model)
    stats = None
    if calib_ids is not None:
        if is_moe_model(model) or not hasattr(model, "model"):
            raise NotImplementedError(
                "activation-calibrated smoothing needs the dense "
                "Llama-family capture forward; smooth MoE models without "
                "calib_ids (weight-balancing heuristic)")
        stats = _capture_calib(model, jnp.asarray(calib_ids))

    def a_x(li, key, k):
        if stats is None:
            return jnp.ones((k,), jnp.float32)
        act = stats[li][key]                        # [M, K] float32
        return jnp.maximum(jnp.asarray(np.abs(act).max(axis=0)), 1e-8)

    for li, lyr in enumerate(bb.layers):
        att = lyr.self_attn
        h = att.qkv_proj.shape[0]
        s = _smooth_scale(a_x(li, "qkv", h), att.qkv_proj, alpha)
        (att.qkv_proj,) = _fold(lyr.input_layernorm, s, att.qkv_proj)

        blk = lyr.moe if hasattr(lyr, "moe") else lyr.mlp
        if hasattr(blk, "experts"):
            gu = blk.experts.gate_up                # [E, H, 2I]
            s = _smooth_scale(a_x(li, "gate_up", gu.shape[1]), gu, alpha)
            # the router reads the SAME normed activations — scale it
            # too or routing decisions would shift under smoothing
            blk.experts.gate_up, blk.gate_w = _fold(
                lyr.post_attention_layernorm, s, gu, blk.gate_w)
        else:
            gu = blk.gate_up_proj
            s = _smooth_scale(a_x(li, "gate_up", gu.shape[0]), gu, alpha)
            (blk.gate_up_proj,) = _fold(
                lyr.post_attention_layernorm, s, gu)
    model._smoothed = True
    return model


# ---- engine-facing entry point ----------------------------------------------

def quantized_weight_bytes(model) -> int:
    """HBM bytes of the quantized projections/head (codes + scales)."""
    total = 0
    for lyr in _backbone(model).layers:
        for obj in (lyr.self_attn,
                    lyr.moe if hasattr(lyr, "moe") else lyr.mlp,
                    getattr(lyr, "moe", None) and lyr.moe.experts):
            for v in (vars(obj).values() if obj is not None else ()):
                if isinstance(v, (QuantizedWeight, QuantizedExpertStack)):
                    total += v.nbytes()
    head = getattr(model, "lm_head", None)
    if isinstance(head, QuantizedWeight):
        total += head.nbytes()
    return total


def quantize_for_serving(model, algo: str = "weight_only_int8", *,
                         calib_ids=None, smooth: bool = False,
                         smooth_alpha: float = 0.5,
                         percdamp: float = 0.01):
    """Weight-only quantize a model IN PLACE for the paged serving stack.

    Structure-agnostic over the ``models/paged.py`` adapter seam: dense
    Llama-family projections (qkv/o/gate_up/down + untied lm_head)
    become :class:`~paddle_tpu.quantization.QuantizedWeight` (the paged
    forwards already dispatch through ``wo_matmul``); MoE expert stacks
    become :class:`QuantizedExpertStack` (dequantized on the fly by
    ``distributed.moe``); the fp32 router gate is NEVER quantized
    (routing decisions are cheap and precision-critical).

    ``algo``: weight_only_int8 | weight_only_int4 | gptq_int8 |
    gptq_int4 (GPTQ needs ``calib_ids`` and a dense Llama-family model —
    the Hessian capture forward is structure-specific). ``smooth=True``
    folds :func:`smooth_for_serving` in first.

    Under ``PT_QUANT_WEIGHTS=0`` this is the identity (the model is
    returned untouched and the gauges report bits=0).
    """
    if not weights_quant_enabled():
        _Q_BITS.set(0)
        return model
    bb = _backbone(model)
    if any(getattr(lyr.self_attn, "fp8_meta", None) is not None
           for lyr in bb.layers):
        raise ValueError(
            "weight-only quantization and the fp8 training path are "
            "mutually exclusive; rebuild the model with fp8=False")
    gptq = algo.startswith("gptq")
    bits = 4 if algo.endswith("int4") else 8
    rtn = f"weight_only_int{bits}"
    moe = is_moe_model(model)

    if smooth:
        smooth_for_serving(model, calib_ids=calib_ids, alpha=smooth_alpha)

    if gptq:
        if moe or not hasattr(model, "model"):
            raise NotImplementedError(
                "GPTQ for serving supports dense Llama-family models "
                "only (the calibration capture forward is "
                "structure-specific); use weight_only_int8/int4")
        quantize_llama_weights(model, algo, calib_ids=calib_ids,
                               percdamp=percdamp)
    else:
        for lyr in bb.layers:
            att = lyr.self_attn
            att.qkv_proj = weight_quantize(att.qkv_proj, rtn)
            att.o_proj = weight_quantize(att.o_proj, rtn)
            blk = lyr.moe if hasattr(lyr, "moe") else lyr.mlp
            if hasattr(blk, "experts"):
                ex = blk.experts
                ex.gate_up = expert_stack_quantize(ex.gate_up, rtn)
                ex.down = expert_stack_quantize(ex.down, rtn)
            else:
                blk.gate_up_proj = weight_quantize(blk.gate_up_proj, rtn)
                blk.down_proj = weight_quantize(blk.down_proj, rtn)
        if getattr(model, "lm_head", None) is not None:
            model.lm_head = weight_quantize(model.lm_head, rtn)

    # roofline/geometry + bench read these back (engine _geom closure)
    model._wo_bits = bits
    _Q_BITS.set(bits)
    _Q_LAYERS.set(len(bb.layers))
    _Q_SMOOTHED.set(1 if getattr(model, "_smoothed", False) else 0)
    try:
        _Q_WEIGHT_BYTES.set(quantized_weight_bytes(model))
    except Exception:
        pass                     # exotic structures: gauge is best-effort
    return model


# ---- quality instrumentation ------------------------------------------------

def quant_quality(ref_logits, q_logits) -> dict:
    """Logit MSE + greedy match-rate of quantized vs reference logits
    (any matching [..., V] shapes). Publishes both gauges and returns
    ``{"logit_mse", "greedy_match_rate"}`` — bench embeds this dict in
    its JSON so quality regressions ride the same history as perf."""
    ref = np.asarray(ref_logits, np.float32)
    q = np.asarray(q_logits, np.float32)
    if ref.shape != q.shape:
        raise ValueError(f"shape mismatch {ref.shape} vs {q.shape}")
    mse = float(np.mean((ref - q) ** 2))
    match = float(np.mean(ref.argmax(-1) == q.argmax(-1)))
    _Q_MSE.set(mse)
    _Q_MATCH.set(match)
    return {"logit_mse": mse, "greedy_match_rate": match}
