"""``paddle.onnx`` surface (ref: ``python/paddle/onnx/export.py``).

ONNX export is a documented out-of-scope gap for the TPU training framework
(SURVEY.md §2.10): there is no onnx runtime in this environment and the
TPU-native interchange format is StableHLO. ``export`` therefore produces a
``jax.export`` StableHLO artifact (portable across XLA runtimes) and raises
with instructions if a literal ``.onnx`` file is required.
"""
from __future__ import annotations

from paddle_tpu.jit import save as _jit_save

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None, **kw):
    """Export ``layer`` to a portable serialized-StableHLO artifact (the
    TPU-native analogue of the reference's ONNX graph). ``opset_version`` is
    accepted for signature parity and ignored."""
    if str(path).endswith(".onnx"):
        # a literal .onnx graph cannot be produced here — never silently
        # hand back a differently-named artifact
        raise NotImplementedError(
            "paddle_tpu does not emit ONNX graphs; it exports StableHLO "
            "(same deploy role). Pass a path without .onnx or use "
            "paddle_tpu.jit.save.")
    return _jit_save(layer, str(path), input_spec=input_spec)
