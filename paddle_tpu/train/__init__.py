from paddle_tpu.train.step import make_train_step, TrainState
from paddle_tpu.train.elastic import ElasticRunner, run_elastic
from paddle_tpu.train.trainer import Trainer, TrainerArgs
from paddle_tpu.train.checkpoint import CheckpointManager
