from paddle_tpu.train.step import make_train_step, TrainState
