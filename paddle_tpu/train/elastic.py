"""Elastic / fault-tolerant training (ref: ``paddle.distributed.fleet.elastic``
and the Fleet controller's restart loop).

The reference restarts dead pods and re-joins collectives; on TPU pods the
scheduler replaces the slice, so elasticity here means: checkpoint
continuously, detect failure (exception, stall, NaN storm), restore the
LATEST checkpoint into a FRESH trainer and continue — bounded restarts with
backoff. Pure host logic over the jitted step (no in-graph state).
"""
from __future__ import annotations

import time
from typing import Callable, Optional


from paddle_tpu.observability import METRICS, instant as _trace_instant
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.utils.watchdog import StallWatchdog, WatchdogTrip

__all__ = ["ElasticRunner", "run_elastic"]

_RESTARTS = METRICS.counter(
    "elastic_restarts_total", "elastic restarts taken after a failure")
_GIVEUPS = METRICS.counter(
    "elastic_giveups_total", "elastic runs abandoned at the restart cap")


class ElasticRunner:
    def __init__(self, make_trainer: Callable[[], "Trainer"],
                 max_restarts: int = 3, backoff_s: float = 5.0,
                 stall_timeout_s: Optional[float] = None):
        self.make_trainer = make_trainer
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.stall_timeout_s = stall_timeout_s
        self.restarts = 0
        self.failures: list[str] = []

    def run(self, data_fn: Callable[[], object], eval_fn=None):
        """``data_fn`` must return a FRESH data iterator per (re)start —
        after a failure the stream is rebuilt, then fast-forwarded by the
        restored step counter via the trainer's resume."""
        while True:
            dog = None
            try:
                # resume INSIDE the restart net: restore already falls
                # back past corrupt checkpoints (CheckpointManager), and
                # a totally unrestorable state still gets bounded retries
                # instead of escaping as an unhandled error
                trainer = self.make_trainer().resume()
                # streams are rebuilt fresh by data_fn each (re)start, so
                # the trainer must fast-forward them to the restored step
                trainer.args.resume_reskip = True
                if self.stall_timeout_s and not trainer.args.ckpt_every:
                    import warnings
                    warnings.warn(
                        "ElasticRunner: stall_timeout_s is set but ckpt_every=0 — "
                        "a stall restart would lose ALL progress. Set "
                        "TrainerArgs(ckpt_every=N) so recovery has checkpoints.")
                if self.stall_timeout_s:
                    # NO emergency save on trip: during a hung step the live
                    # TrainState holds unfulfilled/donated buffers and reading
                    # it from the watchdog thread blocks or throws. Recovery
                    # comes from the trainer's periodic ckpt_every saves.
                    dog = StallWatchdog(self.stall_timeout_s).start()
                    trainer.watchdog = dog  # poked EVERY step inside fit
                out = trainer.fit(data_fn(), eval_fn=eval_fn)
                return out
            except (WatchdogTrip, FloatingPointError, RuntimeError) as e:
                self.failures.append(f"{type(e).__name__}: {e}")
                self.restarts += 1
                _RESTARTS.inc()
                _trace_instant("elastic.restart", restart=self.restarts,
                               cause=type(e).__name__)
                FLIGHT.record("elastic.restart", restart=self.restarts,
                              cause=type(e).__name__)
                if self.restarts > self.max_restarts:
                    _GIVEUPS.inc()
                    FLIGHT.record("elastic.giveup", restarts=self.restarts,
                                  cause=type(e).__name__)
                    FLIGHT.dump(reason="elastic.giveup")
                    raise RuntimeError(
                        f"elastic: gave up after {self.max_restarts} restarts; "
                        f"failures={self.failures}") from e
                time.sleep(self.backoff_s)
            finally:
                if dog is not None:
                    dog.stop()


def run_elastic(make_trainer, data_fn, max_restarts=3, backoff_s=5.0,
                stall_timeout_s=None, eval_fn=None):
    return ElasticRunner(make_trainer, max_restarts, backoff_s,
                         stall_timeout_s).run(data_fn, eval_fn=eval_fn)
