"""Canonical fused training step (ref: the reference's Fleet training loop —
forward/backward/allreduce/optimizer as separate phases; here ONE jitted,
donated XLA program: grads, collectives, optimizer update and LR schedule all
fuse, params stay resident in HBM in their sharded layout).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module, combine, partition_trainable, value_and_grad
from paddle_tpu.distributed.mesh import HybridMesh
from paddle_tpu.distributed.sharded import partition_specs, shard_module
from paddle_tpu.observability.compile import instrumented_jit


@jax.tree_util.register_pytree_node_class
class TrainState:
    """(model, opt_state, step) bundle that flattens as one pytree."""

    def __init__(self, model, opt_state, rng=None):
        self.model = model
        self.opt_state = opt_state
        self.rng = rng

    def tree_flatten(self):
        return (self.model, self.opt_state, self.rng), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def step(self):
        return self.opt_state["step"]


def make_train_step(loss_fn: Callable, optimizer, mesh: Optional[HybridMesh] = None,
                    donate: bool = True, with_rng: bool = False):
    """loss_fn(model, *batch[, rng]) -> scalar. Returns jitted
    step(state, *batch) -> (state, loss)."""

    def step(state: TrainState, *batch):
        if with_rng:
            rng, sub = jax.random.split(state.rng)
            loss, grads = value_and_grad(loss_fn)(state.model, *batch, sub)
        else:
            rng = state.rng
            loss, grads = value_and_grad(loss_fn)(state.model, *batch)
        model, opt_state = optimizer.step(state.model, grads, state.opt_state)
        return TrainState(model, opt_state, rng), loss

    return instrumented_jit(step, name="train.step",
                            donate_argnums=(0,) if donate else ())


def init_state(model: Module, optimizer, mesh: Optional[HybridMesh] = None,
               seed: int = 0) -> TrainState:
    if mesh is not None:
        model = shard_module(model, mesh)
    opt_state = optimizer.init(model)
    if mesh is not None:
        # slots inherit param shardings automatically (they are created by
        # tree_map over sharded params under the mesh context)
        pass
    return TrainState(model, opt_state, jax.random.PRNGKey(seed))
