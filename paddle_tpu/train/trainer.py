"""Trainer (ref: PaddleNLP ``Trainer`` / the reference's Fleet training loop).

One fused jitted step (grads+clip+optimizer+schedule), gradient accumulation
via an inner ``lax.scan``-free accumulation (accumulate in fp32 and apply on
the boundary — keeps one compiled program), watchdog/NaN sentinel hooks, MFU
logging, checkpoint/resume.

Host/device overlap (ISSUE 3): with ``pipeline_depth=K > 0``, ``fit``
keeps a K-deep window of dispatched-but-unfetched steps — XLA's async
dispatch queue executes step N while the host is already feeding steps
N+1..N+K — and the host-side work that needs the loss value (the
``float()`` fetch, NaN guard, fault_value override, watchdog poke, loss
gauge) moves to the DRAIN side of the window with correct (≤K-lagged)
step attribution. Log/eval/checkpoint boundaries drain the window first,
so everything they observe (LR, params, step counter) is exact.
``pipeline_depth=0`` (the default) is the unchanged synchronous loop.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module, combine, partition_trainable, value_and_grad
from paddle_tpu.observability import METRICS, span as _span
from paddle_tpu.observability.compile import instrumented_jit
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.flops import record_throughput
from paddle_tpu.train.checkpoint import CheckpointManager
from paddle_tpu.train.step import TrainState, init_state
from paddle_tpu.utils.faults import fault_point, fault_value

# Training telemetry (ISSUE 2). tokens/sec + MFU ride the SHARED gauges
# in observability.flops (record_throughput) — the same choke point
# bench.py reads, so there is exactly one FLOPs/MFU model.
_STEPS = METRICS.counter("train_steps_total", "optimizer steps completed")
_STEP_S = METRICS.histogram(
    "train_step_seconds", "wall time per training step (host-observed)")
_NAN_SKIPS = METRICS.counter(
    "train_nan_skips_total", "steps skipped on non-finite loss")
_NAN_BACKOFF = METRICS.counter(
    "train_nan_backoff_total", "backoff sleeps taken during NaN streaks")
_LOSS = METRICS.gauge("train_loss", "most recent host-fetched loss")


@dataclass
class TrainerArgs:
    max_steps: int = 1000
    log_every: int = 10
    ckpt_every: int = 0                   # 0 = disabled
    ckpt_dir: str = "checkpoints"
    grad_accum_steps: int = 1
    flops_per_token: float = 0.0          # for MFU logging
    peak_flops: float = 197e12
    nan_guard: bool = True                # skip update & count on non-finite loss
    max_bad_steps: int = 25               # trip watchdog after this many
    # backoff after a SKIPPED (non-finite) step: sleep nan_backoff_s,
    # doubling per consecutive bad step up to nan_backoff_cap_s — a NaN
    # storm from a sick host/chip slows down instead of spinning the
    # accelerator at full rate on poisoned updates. 0 disables.
    nan_backoff_s: float = 0.0
    nan_backoff_cap_s: float = 30.0
    resume_reskip: bool = False           # fast-forward a FRESH stream on resume
    # (leave False when the caller positions the iterator; ElasticRunner
    # always rebuilds streams from scratch and turns this on)
    # host/device overlap: keep up to this many dispatched steps in
    # flight before fetching their losses. 0 = the synchronous loop,
    # bit-identical to the pre-pipelining trainer.
    pipeline_depth: int = 0
    # background checkpoint writes (CheckpointManager(async_save=True)):
    # save() snapshots to host and returns; the tmp+fsync+rename protocol
    # runs on a writer thread. fit() calls mgr.wait() at exit either way.
    async_ckpt: bool = False
    # device-side double-buffered input: while step N executes on the
    # accelerator, step N+1's microbatches are fetched from the iterator
    # and shipped with jax.device_put, so the next dispatch never waits
    # on a host->device transfer. Composes with any pipeline_depth
    # (including 0); the dispatch sequence is unchanged, so losses stay
    # bit-identical to the synchronous loop.
    device_double_buffer: bool = False


class Trainer:
    def __init__(self, model: Module, optimizer, loss_fn: Callable,
                 args: TrainerArgs = None, mesh=None, hooks=None):
        self.args = args or TrainerArgs()
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.state = init_state(model, optimizer, mesh)
        self.hooks = hooks or []
        self._step_fn = self._build_step()
        self.history: list[dict] = []
        self._bad_steps = 0
        self.watchdog = None           # StallWatchdog, poked every step
        # robustness accounting — ElasticRunner and tests read these
        self.stats = {"nan_skips": 0, "bad_streak_max": 0}

    def _build_step(self):
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        accum = self.args.grad_accum_steps
        nan_guard = self.args.nan_guard

        def step(state: TrainState, *batches):
            if accum == 1:
                loss, grads = value_and_grad(loss_fn)(state.model, *batches[0])
            else:
                def acc_body(carry, batch):
                    loss_sum, grads_sum = carry
                    loss, grads = value_and_grad(loss_fn)(state.model, *batch)
                    grads_sum = jax.tree_util.tree_map(
                        lambda a, g: a if g is None else a + g.astype(jnp.float32),
                        grads_sum, grads, is_leaf=lambda x: x is None)
                    return (loss_sum + loss, grads_sum), None

                zero = jax.tree_util.tree_map(
                    lambda p: None if p is None else jnp.zeros(p.shape, jnp.float32),
                    partition_trainable(state.model)[0], is_leaf=lambda x: x is None)
                (loss, grads), _ = jax.lax.scan(
                    acc_body, (jnp.zeros((), jnp.float32), zero),
                    jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches))
                loss = loss / accum
                grads = jax.tree_util.tree_map(
                    lambda g: None if g is None else g / accum,
                    grads, is_leaf=lambda x: x is None)
            new_model, new_opt = optimizer.step(state.model, grads, state.opt_state)
            if nan_guard:
                ok = jnp.isfinite(loss)
                new_model = jax.tree_util.tree_map(
                    lambda new, old: old if new is None else jnp.where(ok, new, old),
                    new_model, state.model, is_leaf=lambda x: x is None)
                new_opt = jax.tree_util.tree_map(
                    lambda new, old: old if new is None else jnp.where(ok, new, old),
                    new_opt, state.opt_state, is_leaf=lambda x: x is None)
            return TrainState(new_model, new_opt, state.rng), loss

        # compile introspection (ISSUE 4): spans + compile_seconds +
        # cache hit/miss counters, and cost_analysis FLOPs that back the
        # MFU gauges when no analytic flops_per_token was configured
        return instrumented_jit(step, name="train.step", donate_argnums=(0,))

    def resume(self):
        mgr = CheckpointManager(self.args.ckpt_dir)
        restored = mgr.restore(self.state)
        if restored is not None:
            self.state = restored
        return self

    def fit(self, data_iter, eval_fn: Optional[Callable] = None):
        try:
            if self.args.pipeline_depth > 0 or self.args.device_double_buffer:
                return self._fit_pipelined(data_iter, eval_fn)
            return self._fit_sync(data_iter, eval_fn)
        except BaseException as e:
            # last event of a dead run; the dump is a no-op unless a
            # flight dir is configured (PT_FLIGHT_DIR / FLIGHT.dir). No
            # int(state.step) here — syncing a poisoned device state in
            # a crash path can hang; FLIGHT already tracks last_step.
            FLIGHT.record("train.crash",
                          error=f"{type(e).__name__}: {e}")
            FLIGHT.dump(reason=f"train.crash:{type(e).__name__}")
            raise

    def _flops_per_token(self, steps: int, tokens: int) -> float:
        """Analytic FLOPs model when configured, else derived from the
        newest XLA cost_analysis estimate of the instrumented step
        (flops-per-call × steps ÷ tokens over the logging window)."""
        if self.args.flops_per_token:
            return self.args.flops_per_token
        fpc = getattr(self._step_fn, "flops_per_call", 0.0)
        if fpc and steps and tokens:
            return fpc * steps / tokens
        return 0.0

    def _fit_sync(self, data_iter, eval_fn: Optional[Callable] = None):
        args = self.args
        mgr = (CheckpointManager(args.ckpt_dir, async_save=args.async_ckpt)
               if args.ckpt_every else None)
        accum = args.grad_accum_steps
        t_last = time.perf_counter()
        tokens_since = 0
        steps_since = 0
        start_step = int(self.state.step)
        if start_step >= args.max_steps:
            return self.state       # already done — consume nothing
        it = iter(data_iter)
        if start_step and args.resume_reskip:
            # align a FRESH stream with the restored step counter — without
            # this a resumed run re-trains the first batches and never sees
            # the tail. Pass resume_reskip=False if the iterator is already
            # positioned.
            for _ in range(start_step * accum):
                next(it)
        for _ in range(start_step, args.max_steps):
            # chaos hooks: train.step may raise (→ elastic restart) or
            # stall (→ StallWatchdog trip); train.loss overrides the host
            # loss value (NaN-storm injection without poisoning data)
            fault_point("train.step", step=int(self.state.step),
                        trainer=self)
            t_step = time.monotonic()
            with _span("train.step", step=int(self.state.step)):
                micro = [self._to_batch(next(it)) for _ in range(accum)]
                self.state, loss = self._step_fn(self.state, *micro)
                if self.watchdog is not None:
                    self.watchdog.poke()   # raises WatchdogTrip if stalled
                step_no = int(self.state.step)
                # the float() fetch blocks on the device step, so the
                # histogram sees real step latency, not dispatch latency
                loss_val = fault_value("train.loss", float(loss),
                                       step=step_no)
            _STEP_S.observe(time.monotonic() - t_step)
            _STEPS.inc()
            _LOSS.set(loss_val)
            FLIGHT.record("train.step", step=step_no, loss=loss_val)

            if args.nan_guard:
                if not np.isfinite(loss_val):
                    # the in-graph guard already kept the params/opt state
                    # of the poisoned update; here we count, back off, and
                    # eventually trip into the elastic restart path
                    self._bad_steps += 1
                    self.stats["nan_skips"] += 1
                    _NAN_SKIPS.inc()
                    FLIGHT.record("train.nan_skip", step=step_no,
                                  streak=self._bad_steps)
                    self.stats["bad_streak_max"] = max(
                        self.stats["bad_streak_max"], self._bad_steps)
                    if self._bad_steps >= args.max_bad_steps:
                        from paddle_tpu.utils.watchdog import WatchdogTrip
                        FLIGHT.record("train.giveup", step=step_no,
                                      streak=self._bad_steps)
                        raise WatchdogTrip(
                            f"{self._bad_steps} consecutive non-finite losses")
                    if args.nan_backoff_s > 0:
                        _NAN_BACKOFF.inc()
                        FLIGHT.record("train.nan_backoff", step=step_no,
                                      streak=self._bad_steps)
                        time.sleep(min(
                            args.nan_backoff_s * 2 ** (self._bad_steps - 1),
                            args.nan_backoff_cap_s))
                else:
                    self._bad_steps = 0

            steps_since += 1
            tokens_since += sum(int(np.prod(b[0].shape[:2])) for b in micro
                                if hasattr(b[0], "shape") and b[0].ndim >= 2)
            if args.log_every and step_no % args.log_every == 0:
                now = time.perf_counter()
                dt = now - t_last
                rec = {"step": step_no, "loss": loss_val,
                       "steps_per_sec": args.log_every / dt if dt > 0 else 0.0,
                       "lr": self.optimizer.get_lr(self.state.opt_state)}
                fpt = self._flops_per_token(steps_since, tokens_since)
                if fpt and tokens_since and dt > 0:
                    rec["tokens_per_sec"] = tokens_since / dt
                    # one MFU model for trainer, StepTimer, and bench.py:
                    # the shared gauges in observability.flops
                    rec["mfu"] = record_throughput(
                        tokens_since / dt, fpt, args.peak_flops)
                self.history.append(rec)
                for h in self.hooks:
                    h(rec)
                t_last, tokens_since, steps_since = now, 0, 0
            if mgr and step_no % args.ckpt_every == 0:
                mgr.save(step_no, self.state)
            if eval_fn and args.log_every and step_no % (args.log_every * 10) == 0:
                eval_fn(self.state.model)
        if mgr is not None:
            mgr.wait()     # async mode: "fit returned" implies durable
        return self.state

    # ------------------------------------------------- pipelined fit path
    def _fit_pipelined(self, data_iter, eval_fn: Optional[Callable] = None):
        """The deferred-sync loop. Invariants vs the synchronous path:

        * the DISPATCH sequence (batch order, jitted calls, donation
          chain) is identical, so per-step losses are bit-identical;
        * every host decision that needs a loss value happens at drain
          time, attributed to the step that produced it — a host step
          mirror tracks the in-graph counter (which does NOT advance on
          a non-finite loss when nan_guard holds the update);
        * log/ckpt/eval fire only with the window empty, so they see
          exactly the state the synchronous loop would have seen.
        """
        args = self.args
        depth = args.pipeline_depth
        mgr = (CheckpointManager(args.ckpt_dir, async_save=args.async_ckpt)
               if args.ckpt_every else None)
        accum = args.grad_accum_steps
        start_step = int(self.state.step)
        if start_step >= args.max_steps:
            return self.state
        it = iter(data_iter)
        if start_step and args.resume_reskip:
            for _ in range(start_step * accum):
                next(it)

        window: deque = deque()   # (loss_handle, t_dispatch, n_tokens)
        drained = start_step      # host mirror of the device step counter
        last_loss = float("nan")
        t_last = time.perf_counter()
        tokens_since = 0
        steps_since = 0
        # host input/dispatch seconds that rode in the shadow of in-flight
        # device steps this logging window — the overlap-aware MFU
        # (ROADMAP leftover) subtracts them from the wall-clock window
        hidden_host_s = 0.0
        boundary_done = start_step   # last step boundary actions ran for

        def is_boundary(s: int) -> bool:
            if s <= boundary_done:
                return False
            return ((args.log_every and s % args.log_every == 0)
                    or (mgr and s % args.ckpt_every == 0)
                    or (eval_fn is not None and args.log_every
                        and s % (args.log_every * 10) == 0))

        def drain_one():
            nonlocal drained, last_loss, tokens_since, steps_since
            loss, t_disp, ntok = window.popleft()
            with _span("train.drain", step=drained + 1,
                       inflight=len(window) + 1):
                raw = float(loss)         # blocks until the step executed
            if self.watchdog is not None:
                self.watchdog.poke()      # raises WatchdogTrip if stalled
            # in-graph guard held params/opt/step on a non-finite loss, so
            # the device counter did not move — mirror that on the host
            if (not args.nan_guard) or np.isfinite(raw):
                drained += 1
            step_no = drained
            loss_val = fault_value("train.loss", raw, step=step_no)
            _STEP_S.observe(time.monotonic() - t_disp)
            _STEPS.inc()
            _LOSS.set(loss_val)
            FLIGHT.record("train.step", step=step_no, loss=loss_val)
            last_loss = loss_val
            tokens_since += ntok
            steps_since += 1
            if args.nan_guard:
                if not np.isfinite(loss_val):
                    self._bad_steps += 1
                    self.stats["nan_skips"] += 1
                    _NAN_SKIPS.inc()
                    FLIGHT.record("train.nan_skip", step=step_no,
                                  streak=self._bad_steps)
                    self.stats["bad_streak_max"] = max(
                        self.stats["bad_streak_max"], self._bad_steps)
                    if self._bad_steps >= args.max_bad_steps:
                        from paddle_tpu.utils.watchdog import WatchdogTrip
                        FLIGHT.record("train.giveup", step=step_no,
                                      streak=self._bad_steps)
                        raise WatchdogTrip(
                            f"{self._bad_steps} consecutive non-finite losses")
                    if args.nan_backoff_s > 0:
                        _NAN_BACKOFF.inc()
                        FLIGHT.record("train.nan_backoff", step=step_no,
                                      streak=self._bad_steps)
                        time.sleep(min(
                            args.nan_backoff_s * 2 ** (self._bad_steps - 1),
                            args.nan_backoff_cap_s))
                else:
                    self._bad_steps = 0

        def run_boundaries():
            """Log/ckpt/eval for the (fully drained) current step — same
            order and conditions as the synchronous loop."""
            nonlocal t_last, tokens_since, steps_since, hidden_host_s, \
                boundary_done
            step_no = drained
            if step_no <= boundary_done:
                return
            boundary_done = step_no
            if args.log_every and step_no % args.log_every == 0:
                now = time.perf_counter()
                dt = now - t_last
                rec = {"step": step_no, "loss": last_loss,
                       "steps_per_sec": args.log_every / dt if dt > 0 else 0.0,
                       "lr": self.optimizer.get_lr(self.state.opt_state)}
                fpt = self._flops_per_token(steps_since, tokens_since)
                if fpt and tokens_since and dt > 0:
                    rec["tokens_per_sec"] = tokens_since / dt
                    rec["mfu"] = record_throughput(
                        tokens_since / dt, fpt, args.peak_flops,
                        hidden_host_s=hidden_host_s, window_s=dt)
                self.history.append(rec)
                for h in self.hooks:
                    h(rec)
                t_last, tokens_since, steps_since = now, 0, 0
                hidden_host_s = 0.0
            if mgr and step_no % args.ckpt_every == 0:
                # the window is empty: self.state IS step `step_no`
                mgr.save(step_no, self.state)
            if (eval_fn and args.log_every
                    and step_no % (args.log_every * 10) == 0):
                eval_fn(self.state.model)

        dbuf = args.device_double_buffer
        staged_next = None      # step i+1's microbatches, already on device
        for i in range(start_step, args.max_steps):
            # chaos hook rides the dispatch side (an exception here must
            # reach the elastic restart net immediately); the host step
            # prediction replaces int(state.step), which would sync
            fault_point("train.step", step=drained + len(window),
                        trainer=self)
            in_flight_before = len(window)
            t_disp = time.monotonic()
            with _span("train.step", step=drained + len(window)):
                if staged_next is not None:
                    micro, staged_next = staged_next, None
                else:
                    micro = [self._to_batch(next(it)) for _ in range(accum)]
                self.state, loss = self._step_fn(self.state, *micro)
            if in_flight_before > 0:
                # host input/dispatch time spent while device steps were
                # already executing — hidden from the critical path
                hidden_host_s += time.monotonic() - t_disp
            ntok = sum(int(np.prod(b[0].shape[:2])) for b in micro
                       if hasattr(b[0], "shape") and b[0].ndim >= 2)
            window.append((loss, t_disp, ntok))
            if dbuf and i + 1 < args.max_steps:
                # the step just dispatched is executing: fetch the NEXT
                # step's batches and start their host->device transfers
                # now so the next dispatch finds them resident. device_put
                # is async — this overlaps transfer with compute.
                t_pf = time.monotonic()
                staged_next = [
                    tuple(jax.device_put(x) for x in self._to_batch(b))
                    for b in [next(it) for _ in range(accum)]]
                hidden_host_s += time.monotonic() - t_pf
            while len(window) > depth:
                drain_one()
            # drain fully when the just-dispatched step lands on a
            # boundary (host prediction — exact unless a NaN is in
            # flight), or when a mid-window drain revealed one
            if is_boundary(drained + len(window)) or is_boundary(drained):
                while window:
                    drain_one()
                run_boundaries()
        while window:
            drain_one()
        run_boundaries()
        if mgr is not None:
            mgr.wait()     # async mode: "fit returned" implies durable
        return self.state

    @staticmethod
    def _to_batch(b):
        if isinstance(b, (tuple, list)):
            return tuple(jnp.asarray(x) for x in b)
        return (jnp.asarray(b),)
