"""Checkpoint save/load (ref: ``paddle.save``/``paddle.load`` +
Fleet sharded checkpoints / auto-parallel ``dist_saver``).

Two backends:
  * numpy .npz — dependency-free, host-gathered (fine single-host)
  * orbax — sharded, async-capable, multi-host (preferred on pods)

State layout: {model, opt_state, rng, step, meta}. Restore is EXACT —
optimizer slots, RNG key, LR-schedule step all round-trip (SURVEY.md §2.9).
"""
from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module, _path_to_str


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: x is None)
    return [(_path_to_str(p), l) for p, l in flat], treedef


def save(state: Any, path: str) -> None:
    """paddle.save equivalent: any pytree (Module, TrainState, dict) → one file."""
    path = Path(path)
    if path.suffix != ".npz":
        path = Path(str(path) + ".npz")
    path.parent.mkdir(parents=True, exist_ok=True)
    flat, _ = _flatten_with_paths(state)
    arrays = {}
    meta = {"leaves": []}
    for i, (p, leaf) in enumerate(flat):
        if leaf is None:
            meta["leaves"].append({"path": p, "kind": "none"})
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            key = f"a{i}"
            arrays[key] = np.asarray(leaf)
            meta["leaves"].append({"path": p, "kind": "array", "key": key,
                                   "dtype": str(np.asarray(leaf).dtype)})
        else:
            meta["leaves"].append({"path": p, "kind": "py", "value": leaf})
    np.savez(path, __meta__=json.dumps(meta), **arrays)


def load(path: str, target: Any = None) -> Any:
    """paddle.load equivalent. With `target`, restores into the target's
    structure (exact dtypes/shapes checked); without, returns {path: array}."""
    p = str(path)
    if not p.endswith(".npz"):
        p = p + ".npz"
    with np.load(p, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        leaves_meta = meta["leaves"]
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    by_path = {}
    for lm in leaves_meta:
        if lm["kind"] == "array":
            by_path[lm["path"]] = arrays[lm["key"]]
        elif lm["kind"] == "py":
            by_path[lm["path"]] = lm["value"]
        else:
            by_path[lm["path"]] = None
    if target is None:
        return by_path
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        target, is_leaf=lambda x: x is None)
    new_leaves = []
    for p, leaf in flat:
        ps = _path_to_str(p)
        if ps not in by_path:
            raise KeyError(f"checkpoint missing leaf {ps}")
        val = by_path[ps]
        if isinstance(leaf, (jax.Array, np.ndarray)):
            arr = jnp.asarray(val, dtype=leaf.dtype)
            if arr.shape != leaf.shape:
                raise ValueError(f"{ps}: shape {arr.shape} != {leaf.shape}")
            # preserve sharding of the target leaf
            if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
                arr = jax.device_put(arr, leaf.sharding)
            new_leaves.append(arr)
        else:
            new_leaves.append(val if val is not None else leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


class CheckpointManager:
    """Step-numbered checkpoints with retention (ref Fleet auto ckpt)."""

    def __init__(self, directory: str, max_to_keep: int = 3, use_orbax: bool = False):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.use_orbax = use_orbax
        if use_orbax:
            import orbax.checkpoint as ocp
            self._mgr = ocp.CheckpointManager(
                self.dir, options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def _step_path(self, step: int) -> Path:
        return self.dir / f"ckpt_{step:08d}.npz"

    def save(self, step: int, state) -> None:
        if self.use_orbax:
            import orbax.checkpoint as ocp
            self._mgr.save(step, args=ocp.args.StandardSave(
                jax.tree_util.tree_map(np.asarray, state,
                                       is_leaf=lambda x: x is None)))
            self._mgr.wait_until_finished()
            return
        save(state, self._step_path(step))
        self._gc()

    def latest_step(self) -> Optional[int]:
        if self.use_orbax:
            return self._mgr.latest_step()
        steps = sorted(int(p.stem.split("_")[1]) for p in self.dir.glob("ckpt_*.npz"))
        return steps[-1] if steps else None

    def restore(self, state_like, step: Optional[int] = None):
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if self.use_orbax:
            import orbax.checkpoint as ocp
            restored = self._mgr.restore(step, args=ocp.args.StandardRestore(
                jax.tree_util.tree_map(np.asarray, state_like,
                                       is_leaf=lambda x: x is None)))
            flat_new = jax.tree_util.tree_leaves(restored, is_leaf=lambda x: x is None)
            _, treedef = jax.tree_util.tree_flatten(state_like, is_leaf=lambda x: x is None)
            return jax.tree_util.tree_unflatten(treedef, [
                jnp.asarray(n, dtype=o.dtype) if isinstance(o, (jax.Array, np.ndarray)) else n
                for n, o in zip(flat_new, jax.tree_util.tree_leaves(
                    state_like, is_leaf=lambda x: x is None))])
        return load(self._step_path(step), target=state_like)

    def _gc(self):
        ckpts = sorted(self.dir.glob("ckpt_*.npz"))
        while len(ckpts) > self.max_to_keep:
            ckpts.pop(0).unlink()


def save_state_dict(module: Module, path: str):
    """paddle-style: save only the state dict."""
    save(dict(module.state_dict()), path)


def load_state_dict(module: Module, path: str):
    sd = load(path)
    module.set_state_dict(sd)
    return module
