"""Checkpoint save/load (ref: ``paddle.save``/``paddle.load`` +
Fleet sharded checkpoints / auto-parallel ``dist_saver``).

Two backends:
  * numpy .npz — dependency-free, host-gathered (fine single-host)
  * orbax — sharded, async-capable, multi-host (preferred on pods)

State layout: {model, opt_state, rng, step, meta}. Restore is EXACT —
optimizer slots, RNG key, LR-schedule step all round-trip (SURVEY.md §2.9).

Durability (elastic restore is only as good as the last durable
checkpoint — PAPER.md §2.9):
  * ATOMIC save — write to a same-directory tmp file, fsync, then
    ``os.replace`` + directory fsync. A crash mid-save leaves at worst a
    stale ``.tmp`` file; the previous checkpoint is never damaged.
  * VERIFIED load — every array carries a CRC32 in the meta blob,
    checked on read; truncated/bit-rotted files raise
    :class:`CheckpointCorruptError` instead of restoring garbage.
  * ``CheckpointManager`` keeps ``max_to_keep`` checkpoints plus a
    ``latest`` pointer that only advances after the durable rename, and
    ``restore`` falls back to the newest VERIFIABLE checkpoint when the
    latest is corrupt/unreadable.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module, _path_to_str
from paddle_tpu.observability import METRICS, span as _span
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.utils.faults import fault_point

# Checkpoint telemetry (ISSUE 2): durations/bytes of successful saves
# and restores (failed attempts surface via faults_injected_total and
# the corruption counters, not as latency samples).
_SAVE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0)
_CKPT_SAVES = METRICS.counter("ckpt_saves_total", "durable checkpoint saves")
_CKPT_RESTORES = METRICS.counter(
    "ckpt_restores_total", "successful checkpoint restores")
_CKPT_SAVE_S = METRICS.histogram(
    "ckpt_save_seconds", "wall time of one durable save",
    buckets=_SAVE_BUCKETS)
_CKPT_RESTORE_S = METRICS.histogram(
    "ckpt_restore_seconds", "wall time of one verified load",
    buckets=_SAVE_BUCKETS)
_CKPT_BYTES = METRICS.counter(
    "ckpt_saved_bytes_total", "bytes written by durable saves")
_CKPT_LAST_BYTES = METRICS.gauge(
    "ckpt_last_save_bytes", "size of the newest durable checkpoint")
_CKPT_CRC_FAILS = METRICS.counter(
    "ckpt_crc_failures_total", "array CRC mismatches caught on load")
_CKPT_UNREADABLE = METRICS.counter(
    "ckpt_unreadable_total", "checkpoints that failed to parse at all")
_CKPT_ASYNC_INFLIGHT = METRICS.gauge(
    "ckpt_async_in_flight", "background checkpoint writes in flight (0/1 — "
    "at most one save is ever in flight)")


class CheckpointCorruptError(RuntimeError):
    """Checkpoint failed CRC/structure verification on load."""


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: x is None)
    return [(_path_to_str(p), l) for p, l in flat], treedef


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _fsync_dir(path: Path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:            # exotic fs: durability is best-effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save(state: Any, path: str) -> None:
    """paddle.save equivalent: any pytree (Module, TrainState, dict) → one
    file. Crash-safe: the bytes land in ``<name>.tmp`` first and reach the
    final path only through an fsync'd ``os.replace`` — a kill at any
    point leaves either the complete old file or the complete new one."""
    path = Path(path)
    if path.suffix != ".npz":
        path = Path(str(path) + ".npz")
    path.parent.mkdir(parents=True, exist_ok=True)
    flat, _ = _flatten_with_paths(state)
    arrays = {}
    meta = {"leaves": []}
    for i, (p, leaf) in enumerate(flat):
        if leaf is None:
            meta["leaves"].append({"path": p, "kind": "none"})
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            key = f"a{i}"
            arrays[key] = np.asarray(leaf)
            meta["leaves"].append({"path": p, "kind": "array", "key": key,
                                   "dtype": str(np.asarray(leaf).dtype),
                                   "crc": _crc(arrays[key])})
        else:
            meta["leaves"].append({"path": p, "kind": "py", "value": leaf})
    t0 = time.monotonic()
    with _span("ckpt.save", path=str(path)):
        fault_point("ckpt.write", path=str(path))  # injected host I/O error
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
            f.flush()
            os.fsync(f.fileno())
        fault_point("ckpt.rename", path=str(path))    # the crash window
        os.replace(tmp, path)
        _fsync_dir(path.parent)
    nbytes = path.stat().st_size
    _CKPT_SAVES.inc()
    _CKPT_BYTES.inc(nbytes)
    _CKPT_LAST_BYTES.set(nbytes)
    _CKPT_SAVE_S.observe(time.monotonic() - t0)


def load(path: str, target: Any = None, verify: bool = True) -> Any:
    """paddle.load equivalent. With `target`, restores into the target's
    structure (exact dtypes/shapes checked); without, returns {path: array}.
    ``verify`` checks each array's stored CRC32 (checkpoints written
    before CRCs existed load unverified) and raises
    :class:`CheckpointCorruptError` on mismatch or an unreadable file."""
    with _span("ckpt.restore", path=str(path)):
        return _load_impl(path, target, verify)


def _load_impl(path: str, target: Any, verify: bool) -> Any:
    p = str(path)
    if not p.endswith(".npz"):
        p = p + ".npz"
    t0 = time.monotonic()
    try:
        with np.load(p, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            leaves_meta = meta["leaves"]
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
    except FileNotFoundError:
        raise
    except Exception as e:      # zip/pickle/json damage = corrupt file
        _CKPT_UNREADABLE.inc()
        raise CheckpointCorruptError(f"{p}: unreadable checkpoint "
                                     f"({type(e).__name__}: {e})") from e
    if verify:
        for lm in leaves_meta:
            if lm.get("kind") == "array" and "crc" in lm:
                got = _crc(arrays[lm["key"]])
                if got != lm["crc"]:
                    _CKPT_CRC_FAILS.inc()
                    raise CheckpointCorruptError(
                        f"{p}: CRC mismatch for leaf {lm['path']} "
                        f"(stored {lm['crc']:#010x}, got {got:#010x})")
    by_path = {}
    for lm in leaves_meta:
        if lm["kind"] == "array":
            by_path[lm["path"]] = arrays[lm["key"]]
        elif lm["kind"] == "py":
            by_path[lm["path"]] = lm["value"]
        else:
            by_path[lm["path"]] = None
    if target is None:
        _CKPT_RESTORES.inc()
        _CKPT_RESTORE_S.observe(time.monotonic() - t0)
        return by_path
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        target, is_leaf=lambda x: x is None)
    new_leaves = []
    for p, leaf in flat:
        ps = _path_to_str(p)
        if ps not in by_path:
            raise KeyError(f"checkpoint missing leaf {ps}")
        val = by_path[ps]
        if isinstance(leaf, (jax.Array, np.ndarray)):
            arr = jnp.asarray(val, dtype=leaf.dtype)
            if arr.shape != leaf.shape:
                raise ValueError(f"{ps}: shape {arr.shape} != {leaf.shape}")
            # preserve sharding of the target leaf
            if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
                arr = jax.device_put(arr, leaf.sharding)
            new_leaves.append(arr)
        else:
            new_leaves.append(val if val is not None else leaf)
    out = jax.tree_util.tree_unflatten(treedef, new_leaves)
    _CKPT_RESTORES.inc()
    _CKPT_RESTORE_S.observe(time.monotonic() - t0)
    return out


class CheckpointManager:
    """Step-numbered checkpoints with retention (ref Fleet auto ckpt).

    Durability contract: ``save`` is atomic (see :func:`save`), the
    ``latest`` pointer file advances only AFTER the checkpoint's durable
    rename (itself via fsync'd tmp+replace), and ``restore`` verifies
    CRCs — falling back step-by-step to the newest checkpoint that still
    loads when the latest one is corrupt (``fallback=False`` restores
    strictly the requested step or raises).

    Async mode (``async_save=True``, ISSUE 3): ``save`` snapshots the
    device arrays to host ON THE CALLER'S THREAD (so a later donated
    train step can never race the copy), then hands the whole existing
    tmp+fsync+``os.replace`` protocol to a single background writer
    thread and returns. The durability invariants are untouched — the
    ``latest`` pointer still advances only after the durable rename,
    inside the writer. At most one save is ever in flight (a second
    ``save`` first waits out the previous one); ``wait()`` joins the
    writer and re-raises anything it threw. "save returned" therefore
    means "snapshot taken", NOT "durable" — call ``wait()`` for that."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: bool = False, async_save: bool = False):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.use_orbax = use_orbax
        self.async_save = async_save
        self.last_restored_step: Optional[int] = None
        self._writer: Optional[threading.Thread] = None
        self._writer_exc: Optional[BaseException] = None
        if use_orbax:
            import orbax.checkpoint as ocp
            self._mgr = ocp.CheckpointManager(
                self.dir, options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def _step_path(self, step: int) -> Path:
        return self.dir / f"ckpt_{step:08d}.npz"

    def _write_latest(self, step: int):
        tmp = self.dir / "latest.tmp"
        with open(tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.dir / "latest")
        _fsync_dir(self.dir)

    def save(self, step: int, state) -> None:
        if self.use_orbax:
            # same instruments as the native path (ROADMAP leftover) —
            # an operator must not lose ckpt telemetry by switching
            # backends. Import stays inside the branch: tier-1 is
            # orbax-free.
            import orbax.checkpoint as ocp
            t0 = time.monotonic()
            with _span("ckpt.save", backend="orbax", step=step):
                self._mgr.save(step, args=ocp.args.StandardSave(
                    jax.tree_util.tree_map(np.asarray, state,
                                           is_leaf=lambda x: x is None)))
                self._mgr.wait_until_finished()
            _CKPT_SAVES.inc()
            _CKPT_SAVE_S.observe(time.monotonic() - t0)
            nbytes = self._orbax_step_bytes(step)
            if nbytes:
                _CKPT_BYTES.inc(nbytes)
                _CKPT_LAST_BYTES.set(nbytes)
            FLIGHT.record("ckpt.save", step=step, backend="orbax")
            return
        if self.async_save:
            return self._save_async(step, state)
        save(state, self._step_path(step))
        # pointer AFTER the durable rename: a kill anywhere before this
        # line leaves ``latest`` on the previous good checkpoint
        self._write_latest(step)
        self._gc()
        FLIGHT.record("ckpt.save", step=step)

    def _orbax_step_bytes(self, step: int) -> int:
        """On-disk size of one orbax step directory (0 when the layout
        is not where we expect it — size is advisory telemetry only)."""
        try:
            d = self.dir / str(step)
            if not d.is_dir():
                return 0
            return sum(p.stat().st_size for p in d.rglob("*") if p.is_file())
        except OSError:
            return 0

    def _save_async(self, step: int, state) -> None:
        # one save in flight, ever: a prior writer finishes (and its
        # failure surfaces HERE) before the next snapshot is taken
        self.wait()
        # device→host copy on the caller's thread: after this returns the
        # snapshot shares nothing with the live (donated) TrainState.
        # np.asarray on a jax.Array materializes a fresh host buffer, but
        # on an ndarray it aliases — host leaves need the explicit copy
        snapshot = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True) if isinstance(x, np.ndarray)
            else np.asarray(x) if isinstance(x, jax.Array) else x,
            state, is_leaf=lambda x: x is None)
        _CKPT_ASYNC_INFLIGHT.set(1)

        def _write():
            try:
                save(snapshot, self._step_path(step))
                # same ordering as the sync path: pointer only after the
                # durable rename — a writer death here leaves ``latest``
                # on the previous good checkpoint
                self._write_latest(step)
                self._gc()
                FLIGHT.record("ckpt.save", step=step, mode="async")
            except BaseException as e:   # surfaced by wait()/next save()
                self._writer_exc = e
            finally:
                _CKPT_ASYNC_INFLIGHT.set(0)

        self._writer = threading.Thread(target=_write, name="pt-ckpt-writer",
                                        daemon=True)
        self._writer.start()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the in-flight background save (if any) is durable;
        re-raise anything the writer threw. No-op in sync mode. Tests and
        ``Trainer.fit`` (at exit) call this — it is the only point where
        "the checkpoint is on disk" is guaranteed in async mode."""
        t = self._writer
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"checkpoint writer still running after {timeout}s")
            self._writer = None
        if self._writer_exc is not None:
            exc, self._writer_exc = self._writer_exc, None
            raise exc

    def all_steps(self) -> list:
        return sorted(int(p.stem.split("_")[1])
                      for p in self.dir.glob("ckpt_*.npz"))

    def latest_step(self) -> Optional[int]:
        if self.use_orbax:
            return self._mgr.latest_step()
        ptr = self.dir / "latest"
        if ptr.exists():
            try:
                step = int(ptr.read_text().strip())
                if self._step_path(step).exists():
                    return step
            except (ValueError, OSError):
                pass           # damaged pointer: fall back to the glob
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, state_like, step: Optional[int] = None,
                fallback: bool = True):
        if self.use_orbax:
            step = step if step is not None else self._mgr.latest_step()
            if step is None:
                return None
            import orbax.checkpoint as ocp
            t0 = time.monotonic()
            with _span("ckpt.restore", backend="orbax", step=step):
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(
                        jax.tree_util.tree_map(
                            np.asarray, state_like,
                            is_leaf=lambda x: x is None)))
            flat_new = jax.tree_util.tree_leaves(restored, is_leaf=lambda x: x is None)
            _, treedef = jax.tree_util.tree_flatten(state_like, is_leaf=lambda x: x is None)
            self.last_restored_step = step
            out = jax.tree_util.tree_unflatten(treedef, [
                jnp.asarray(n, dtype=o.dtype) if isinstance(o, (jax.Array, np.ndarray)) else n
                for n, o in zip(flat_new, jax.tree_util.tree_leaves(
                    state_like, is_leaf=lambda x: x is None))])
            _CKPT_RESTORES.inc()
            _CKPT_RESTORE_S.observe(time.monotonic() - t0)
            return out
        if step is not None:
            # explicit step: strict — restoring some OTHER step than the
            # one asked for would be silent time-travel
            out = load(self._step_path(step), target=state_like)
            self.last_restored_step = step
            return out
        start = self.latest_step()
        if start is None:
            return None
        if not fallback:
            out = load(self._step_path(start), target=state_like)
            self.last_restored_step = start
            return out
        candidates = [s for s in reversed(self.all_steps()) if s <= start]
        errors = []
        for s in candidates:
            try:
                out = load(self._step_path(s), target=state_like)
                self.last_restored_step = s
                if errors:
                    import warnings
                    warnings.warn(
                        f"CheckpointManager: fell back to step {s} — newer "
                        f"checkpoint(s) failed verification: {errors}")
                return out
            except (CheckpointCorruptError, OSError, KeyError,
                    ValueError) as e:
                errors.append(f"step {s}: {type(e).__name__}: {e}")
        raise CheckpointCorruptError(
            f"no loadable checkpoint in {self.dir} (tried "
            f"{candidates}); failures: {errors}")

    def _gc(self):
        """keep_last_n retention — never deletes the checkpoint the
        ``latest`` pointer references (it is always the newest)."""
        ckpts = sorted(self.dir.glob("ckpt_*.npz"))
        while len(ckpts) > self.max_to_keep:
            ckpts.pop(0).unlink()


def save_state_dict(module: Module, path: str):
    """paddle-style: save only the state dict."""
    save(dict(module.state_dict()), path)


def load_state_dict(module: Module, path: str):
    sd = load(path)
    module.set_state_dict(sd)
    return module
