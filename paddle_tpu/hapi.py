"""High-level API (ref: ``python/paddle/hapi/model.py`` — ``paddle.Model``
with prepare/fit/evaluate/predict/save/load).

A thin orchestration layer over the fused train step: same ergonomics as the
reference, but each epoch runs ONE compiled program per step and the loop
overlaps host batching with device compute (async dispatch).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module, value_and_grad
from paddle_tpu.summary_utils import flops, summary  # noqa: F401 (ref hapi exports)
from paddle_tpu.train.checkpoint import load_state_dict, save_state_dict
from paddle_tpu.train.step import TrainState, init_state


class Model:
    def __init__(self, network: Module):
        self.network = network
        self.optimizer = None
        self.loss = None
        self.metrics: Sequence = ()
        self._state = None
        self._step_fn = None

    def prepare(self, optimizer=None, loss=None, metrics=None):
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics or ()
        if optimizer is not None:
            self._state = init_state(self.network, optimizer)

            def step(state, x, y):
                def loss_fn(m, x, y):
                    out = m(*x) if isinstance(x, tuple) else m(x)
                    return self.loss(out, y)
                lv, grads = value_and_grad(loss_fn)(state.model, x, y)
                model, opt_state = optimizer.step(state.model, grads, state.opt_state)
                return TrainState(model, opt_state, state.rng), lv

            self._step_fn = jax.jit(step, donate_argnums=(0,))
        return self

    def fit(self, train_data, eval_data=None, epochs=1, verbose=1,
            log_freq=50, callbacks=None):
        from paddle_tpu.callbacks import CallbackList, ProgBarLogger
        callbacks = list(callbacks or ())
        if verbose and not any(isinstance(c, ProgBarLogger) for c in callbacks):
            # reference hapi injects the logger too — all step logging goes
            # through callbacks, no inline prints
            callbacks.insert(0, ProgBarLogger(log_freq=log_freq, verbose=verbose))
        cbs = CallbackList(callbacks, model=self,
                           params={"epochs": epochs, "verbose": verbose})
        history = []
        cbs.on_train_begin()
        for epoch in range(epochs):
            cbs.on_epoch_begin(epoch)
            lv = None
            for i, batch in enumerate(train_data):
                x, y = batch[0], batch[1]
                cbs.on_train_batch_begin(i)
                self._state, lv = self._step_fn(
                    self._state, self._as_args(x), self._as_labels(y))
                if i % log_freq == 0:
                    history.append({"epoch": epoch, "step": i, "loss": float(lv)})
                # callbacks get the device scalar and sync only if they read
                # it — keeps dispatch async between logging steps
                cbs.on_train_batch_end(i, logs={"loss": lv})
            self.network = self._state.model
            logs = {"loss": float(lv) if lv is not None else None}
            if eval_data is not None:
                cbs.on_eval_begin()
                ev = self.evaluate(eval_data, verbose=0)
                cbs.on_eval_end(logs=ev)
                logs.update(ev)
                history.append({"epoch": epoch, **ev})
            cbs.on_epoch_end(epoch, logs=logs)
            if cbs.stop_training:
                break
        cbs.on_train_end()
        return history

    def evaluate(self, eval_data, verbose=1):
        for m in self.metrics:
            m.reset()
        losses = []
        for batch in eval_data:
            x, y = batch[0], batch[1]
            out = self._eval_forward(*self._as_args(x))
            y = self._as_labels(y)
            if self.loss is not None:
                losses.append(float(self.loss(out, y)))
            for m in self.metrics:
                # reference contract: compute() pre-processes, then update;
                # single-tensor returns go to update as one argument
                res_c = m.compute(out, y)
                if not isinstance(res_c, (tuple, list)):
                    res_c = (res_c,)
                m.update(*[np.asarray(t) for t in res_c])
        res = {"eval_loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            res[f"eval_{m.name()}"] = m.accumulate()
        if verbose:
            print(res)
        return res

    def predict(self, test_data):
        return [np.asarray(self._eval_forward(
            *self._as_args(b[0] if isinstance(b, (tuple, list)) else b)))
            for b in test_data]

    def save(self, path):
        net = self._state.model if self._state is not None else self.network
        save_state_dict(net, path)

    def load(self, path):
        load_state_dict(self.network, path)
        if self.optimizer is not None:
            self._state = init_state(self.network, self.optimizer)
        return self

    # -- reference batch-level API (ref hapi/model.py) ----------------------

    def train_batch(self, inputs, labels):
        """One optimizer step on a single batch; returns [loss] like the
        reference. Multi-input networks receive every element of a
        list/tuple ``inputs``."""
        xs = self._as_args(inputs)
        y = self._as_labels(labels)
        self._state, lv = self._step_fn(self._state, xs, y)
        self.network = self._state.model
        return [float(lv)]

    _fwd_jit = None

    @staticmethod
    def _as_args(inputs):
        """Normalise the reference's input convention: a list/tuple is a
        multi-input network's full argument list, else one array."""
        if isinstance(inputs, (list, tuple)):
            return tuple(jnp.asarray(i) for i in inputs)
        return (jnp.asarray(inputs),)

    @staticmethod
    def _as_labels(labels):
        """Single label array, or the tuple of label arrays for multi-label
        losses (symmetric with _as_args)."""
        if isinstance(labels, (list, tuple)):
            if len(labels) == 1:
                return jnp.asarray(labels[0])
            return tuple(jnp.asarray(l) for l in labels)
        return jnp.asarray(labels)

    def _eval_forward(self, *xs):
        """Eval-mode forward through ONE cached jit (training flags restored
        afterwards so the train step does not retrace)."""
        model = self._state.model if self._state is not None else self.network
        if Model._fwd_jit is None:
            Model._fwd_jit = jax.jit(lambda m, *v: m(*v))
        modes = [m.training for m in model.sublayers(include_self=True)]
        model.eval()
        try:
            return Model._fwd_jit(model, *xs)
        finally:
            for sub, was in zip(model.sublayers(include_self=True), modes):
                object.__setattr__(sub, "training", was)

    def eval_batch(self, inputs, labels):
        y = self._as_labels(labels)
        out = self._eval_forward(*self._as_args(inputs))
        return [float(self.loss(out, y))] if self.loss is not None else [out]

    def predict_batch(self, inputs):
        return [np.asarray(self._eval_forward(*self._as_args(inputs)))]

    def parameters(self):
        net = self._state.model if self._state is not None else self.network
        return list(net.parameters())

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)
