"""Training callbacks (ref: ``python/paddle/hapi/callbacks.py``).

Same hook surface as the reference (on_train_begin/…/on_epoch_end etc.),
driven by :class:`paddle_tpu.hapi.Model.fit` and usable from
``paddle_tpu.train.Trainer``. Host-side by design — callbacks observe
scalars the step already syncs, never injecting host work into the
compiled path.
"""
from __future__ import annotations

import math
import os
import time

import numpy as np

__all__ = [
    "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
    "EarlyStopping", "LRSchedulerCallback", "LRScheduler", "ReduceLROnPlateau",
    "VisualDL",
]


def _scheduler_of(model):
    """The LRScheduler attached to the owning Model/Trainer's optimizer —
    optimizers store it as ``optimizer.learning_rate`` (see optimizer/__init__)."""
    from paddle_tpu.optimizer.lr import LRScheduler
    lr = getattr(getattr(model, "optimizer", None), "learning_rate", None)
    return lr if isinstance(lr, LRScheduler) else None


class Callback:
    """Hook base (ref hapi/callbacks.py:Callback). ``model`` is the owning
    Model/Trainer; ``params`` carries epochs/steps metadata."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=(), model=None, params=None):
        self.callbacks = list(callbacks or ())
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)
        self.stop_training = False

    def _fire(self, name, *args, logs=None):
        for c in self.callbacks:
            getattr(c, name)(*args, logs if logs is not None else {})
            if getattr(c, "stop_training", False):
                self.stop_training = True

    def on_train_begin(self, logs=None): self._fire("on_train_begin", logs=logs)
    def on_train_end(self, logs=None): self._fire("on_train_end", logs=logs)
    def on_epoch_begin(self, e, logs=None): self._fire("on_epoch_begin", e, logs=logs)
    def on_epoch_end(self, e, logs=None): self._fire("on_epoch_end", e, logs=logs)
    def on_train_batch_begin(self, s, logs=None): self._fire("on_train_batch_begin", s, logs=logs)
    def on_train_batch_end(self, s, logs=None): self._fire("on_train_batch_end", s, logs=logs)
    def on_eval_begin(self, logs=None): self._fire("on_eval_begin", logs=logs)
    def on_eval_end(self, logs=None): self._fire("on_eval_end", logs=logs)


class ProgBarLogger(Callback):
    """Step/epoch console logger (ref ProgBarLogger; plain-line output
    instead of a terminal progress bar — robust in non-tty jobs)."""

    def __init__(self, log_freq=10, verbose=1):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.monotonic()
        self._seen = 0

    def on_train_batch_end(self, step, logs=None):
        self._seen += 1
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in (logs or {}).items():
                try:  # accept python/numpy/jax scalars alike
                    f = float(np.asarray(v).reshape(-1)[0])
                except (TypeError, ValueError, IndexError):
                    continue
                if not math.isnan(f):
                    items.append(f"{k}: {f:.4f}")
            print(f"[epoch {self._epoch}] step {step} " + " ".join(items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.monotonic() - self._t0
            rate = self._seen / dt if dt > 0 else float("inf")
            print(f"[epoch {epoch}] done in {dt:.1f}s ({rate:.1f} steps/s)")


class ModelCheckpoint(Callback):
    """Periodic save (ref ModelCheckpoint): every ``save_freq`` epochs into
    ``save_dir/{epoch}``, plus ``save_dir/final`` at train end."""

    def __init__(self, save_freq=1, save_dir="checkpoints"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (ref EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, min_delta=0,
                 baseline=None, verbose=1):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor or monitor.endswith("auc") else "min"
        self.mode = mode
        self.stop_training = False

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.stop_training = False  # reset so the instance is reusable
        self.best = (self.baseline if self.baseline is not None
                     else (math.inf if self.mode == "min" else -math.inf))

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement for "
                          f"{self.wait} epochs (best {self.best:.6f})")


class LRSchedulerCallback(Callback):
    """Advance an epoch-granularity LR scheduler (ref LRScheduler callback).

    Step-granularity schedules are compiled into the train step in this
    framework; this callback exists for epoch-driven schedules like
    StepDecay/MultiStepDecay attached to the optimizer.
    """

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_epoch = by_epoch and not by_step

    def on_epoch_end(self, epoch, logs=None):
        sched = _scheduler_of(self.model)
        if self.by_epoch and sched is not None:
            sched.step()


class ReduceLROnPlateau(Callback):
    """Callback flavour of the ReduceOnPlateau scheduler (ref
    hapi/callbacks.py:ReduceLROnPlateau) — drives
    ``optimizer.lr_scheduler.step(metric)`` with the monitored value."""

    def __init__(self, monitor="loss"):
        super().__init__()
        self.monitor = monitor

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        sched = _scheduler_of(self.model)
        if cur is not None and sched is not None:
            sched.step(float(np.asarray(cur).reshape(-1)[0]))



# reference name: paddle.callbacks.LRScheduler
LRScheduler = LRSchedulerCallback


class VisualDL(Callback):
    """Ref callbacks.VisualDL. The visualdl package is not in this
    environment, so scalars stream to JSONL under ``log_dir`` — readable by
    any dashboard and by `jq`."""

    def __init__(self, log_dir="vdl_log", log_freq=20):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = max(1, log_freq)  # syncing every batch would stall
        self._fh = None                   # the async dispatch pipeline
        self._step = 0

    def _write(self, tag, value, step):
        import json as _json
        import os as _os
        if self._fh is None:
            _os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(_os.path.join(self.log_dir, "scalars.jsonl"), "a")
        self._fh.write(_json.dumps(
            {"tag": tag, "value": float(value), "step": int(step)}) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % self.log_freq:
            return  # don't force a device sync on every batch
        for k, v in (logs or {}).items():
            try:
                self._write(f"train/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._write(f"eval/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
