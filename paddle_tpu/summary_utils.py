"""Model summary + FLOPs (ref: ``python/paddle/hapi/model_summary.py`` and
``python/paddle/hapi/dynamic_flops.py``).

``summary`` walks the pytree module (no forward hooks needed — structure is
static) and shape-infers the output with ``jax.eval_shape`` (zero FLOPs, no
device memory). ``flops`` asks XLA's compiled cost model instead of the
reference's hand-maintained per-layer FLOP table — exact for whatever the
model actually lowers to.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["summary", "flops"]


def summary(net, input_size=None, dtypes=None, input=None, print_fn=print):
    """Layer table + parameter counts (ref ``paddle.summary``).

    ``input_size``: shape tuple or list of shape tuples (batch dim included,
    None → 1). Returns {'total_params': .., 'trainable_params': ..,
    'output_shape': ..}.
    """
    owned = {}  # id(module) -> direct param count (buffers excluded, so the
    # column sums to the num_parameters() total)
    for _path, name, leaf, owner in net._iter_named():
        if hasattr(leaf, "shape") and name not in owner._buffers:
            owned[id(owner)] = owned.get(id(owner), 0) + int(np.prod(leaf.shape))

    lines = ["-" * 64,
             f"{'Layer (type)':<40}{'Param #':>20}",
             "=" * 64]
    for mod in net.sublayers(include_self=True):
        lines.append(f"{type(mod).__name__:<40}{owned.get(id(mod), 0):>20,}")
    total = net.num_parameters()
    lines.append("=" * 64)
    lines.append(f"Total params: {total:,}")

    out_desc = None
    if input_size is not None or input is not None:
        if input is not None:
            args = input if isinstance(input, (list, tuple)) else (input,)
            specs = [jax.ShapeDtypeStruct(jnp.asarray(a).shape,
                                          jnp.asarray(a).dtype) for a in args]
        else:
            if not input_size:
                raise ValueError("summary() needs a non-empty input_size")
            sizes = (input_size if isinstance(input_size[0], (list, tuple))
                     else [input_size])
            dts = dtypes or [jnp.float32] * len(sizes)
            specs = [jax.ShapeDtypeStruct(
                tuple(1 if d is None else d for d in s), dt)
                for s, dt in zip(sizes, dts)]
        out = jax.eval_shape(lambda *xs: net(*xs), *specs)
        out_desc = jax.tree_util.tree_map(lambda s: tuple(s.shape), out)
        lines.append(f"Output shape: {out_desc}")
    lines.append("-" * 64)
    if print_fn:
        print_fn("\n".join(lines))
    return {"total_params": total, "trainable_params": total,
            "output_shape": out_desc}


def flops(net, input_size=None, inputs=None, print_fn=print):
    """FLOPs of one forward pass from XLA's compiled cost analysis (ref
    ``paddle.flops``; here exact-for-the-lowering instead of a per-layer
    estimate table). Returns total FLOPs as an int (0 if the backend does
    not expose a cost model)."""
    if inputs is None:
        if not input_size:
            raise ValueError("flops() needs input_size or inputs")
        sizes = (input_size if isinstance(input_size[0], (list, tuple))
                 else [input_size])
        inputs = [jnp.zeros(tuple(1 if d is None else d for d in s),
                            jnp.float32) for s in sizes]
    elif not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    fn = jax.jit(lambda m, *xs: m(*xs))
    compiled = fn.lower(net, *inputs).compile()
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        total = int(cost.get("flops", 0))
    except Exception:
        total = 0
    if print_fn:
        print_fn(f"FLOPs: {total:,}")
    return total
