"""Probability distributions (ref: ``python/paddle/distribution/``).

Same namespace and method surface as the reference (``sample``, ``rsample``,
``log_prob``, ``prob``, ``entropy``, ``mean``, ``variance``,
``kl_divergence``/``register_kl``), rebuilt on ``jax.random`` — samplers take
an optional ``rng`` key and fall back to the framework's seeded global
stream, so eager code matches the reference's stateful API while jitted code
can thread keys explicitly.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.random import next_key

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical", "Beta",
    "Dirichlet", "Gamma", "Exponential", "Laplace", "LogNormal", "Gumbel",
    "Geometric", "Multinomial", "Cauchy", "StudentT", "Poisson",
    "TransformedDistribution", "Transform", "AffineTransform", "ExpTransform",
    "SigmoidTransform", "TanhTransform", "PowerTransform", "ChainTransform",
    "kl_divergence", "register_kl",
]


def _key(rng):
    return rng if rng is not None else next_key()


def _shape(shape):
    return tuple(shape) if not isinstance(shape, int) else (shape,)


class Distribution:
    """Ref: python/paddle/distribution/distribution.py:Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=(), rng=None):
        return jax.lax.stop_gradient(self.rsample(shape, rng=rng))

    def rsample(self, shape=(), rng=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to(self.scale ** 2, self.batch_shape)

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        eps = jax.random.normal(_key(rng), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape)

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(rng), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None):
        if probs is not None:
            self.probs = jnp.asarray(probs, jnp.float32)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = jnp.asarray(logits, jnp.float32)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1 - self.probs)

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.bernoulli(_key(rng), self.probs, shape).astype(jnp.float32)

    def log_prob(self, value):
        # stable bernoulli log pmf from logits
        return value * jax.nn.log_sigmoid(self.logits) + \
            (1 - value) * jax.nn.log_sigmoid(-self.logits)

    def entropy(self):
        p = self.probs
        return -(p * jnp.log(jnp.clip(p, 1e-12, None)) +
                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12, None)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if logits is None:
            self.probs = jnp.asarray(probs, jnp.float32)
            self.probs = self.probs / self.probs.sum(-1, keepdims=True)
            self.logits = jnp.log(jnp.clip(self.probs, 1e-12, None))
        else:
            self.logits = jnp.asarray(logits, jnp.float32)
            self.probs = jax.nn.softmax(self.logits, axis=-1)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.categorical(_key(rng), self.logits, shape=shape)

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, value[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(self.probs * logp, axis=-1)


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        t = self.alpha + self.beta
        return self.alpha * self.beta / (t * t * (t + 1))

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.beta(_key(rng), self.alpha, self.beta, shape)

    def log_prob(self, value):
        from jax.scipy.special import betaln
        return ((self.alpha - 1) * jnp.log(value) +
                (self.beta - 1) * jnp.log1p(-value) -
                betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(-1, keepdims=True)

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.dirichlet(_key(rng), self.concentration, shape)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        a = self.concentration
        return (jnp.sum((a - 1) * jnp.log(value), axis=-1)
                + gammaln(a.sum(-1)) - jnp.sum(gammaln(a), axis=-1))

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnB = jnp.sum(gammaln(a), -1) - gammaln(a0)
        return (lnB + (a0 - k) * digamma(a0)
                - jnp.sum((a - 1) * digamma(a), axis=-1))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / self.rate ** 2

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.gamma(_key(rng), self.concentration, shape) / self.rate

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        a, b = self.concentration, self.rate
        return a * jnp.log(b) + (a - 1) * jnp.log(value) - b * value - gammaln(a)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        return a - jnp.log(self.rate) + gammaln(a) + (1 - a) * digamma(a)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return 1 / self.rate

    @property
    def variance(self):
        return 1 / self.rate ** 2

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.exponential(_key(rng), shape) / self.rate

    def log_prob(self, value):
        return jnp.log(self.rate) - self.rate * value

    def entropy(self):
        return jnp.broadcast_to(1 - jnp.log(self.rate), self.batch_shape)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape)

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return self.loc + self.scale * jax.random.laplace(_key(rng), shape)

    def log_prob(self, value):
        return -jnp.abs(value - self.loc) / self.scale - \
            jnp.log(2 * self.scale)

    def entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape)


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    @property
    def variance(self):
        s2 = self.scale ** 2
        return (jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2)

    def rsample(self, shape=(), rng=None):
        return jnp.exp(self._base.rsample(shape, rng=rng))

    def log_prob(self, value):
        return self._base.log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return self._base.entropy() + self.loc


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return self.loc + self.scale * jnp.float32(0.5772156649015329)

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return self.loc + self.scale * jax.random.gumbel(_key(rng), shape)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.scale) + 1.5772156649015329,
                                self.batch_shape)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (reference convention)."""

    def __init__(self, probs):
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return (1 - self.probs) / self.probs

    @property
    def variance(self):
        return (1 - self.probs) / self.probs ** 2

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(rng), shape, minval=1e-7)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        return value * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def entropy(self):
        p = self.probs
        q = 1 - p
        return -(q * jnp.log(q) + p * jnp.log(p)) / p


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = total_count
        self.probs = jnp.asarray(probs, jnp.float32)
        self.probs = self.probs / self.probs.sum(-1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        logits = jnp.log(jnp.clip(self.probs, 1e-12, None))
        draws = jax.random.categorical(
            _key(rng), logits, shape=(self.total_count,) + shape)
        k = self.probs.shape[-1]
        return jax.nn.one_hot(draws, k).sum(0)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        logp = jnp.log(jnp.clip(self.probs, 1e-12, None))
        return (gammaln(self.total_count + 1.0)
                - jnp.sum(gammaln(value + 1.0), axis=-1)
                + jnp.sum(value * logp, axis=-1))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return self.loc + self.scale * jax.random.cauchy(_key(rng), shape)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z * z))

    def entropy(self):
        return jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                self.batch_shape)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = jnp.asarray(df, jnp.float32)
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return self.loc + self.scale * jax.random.t(_key(rng), self.df, shape)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        d = self.df
        z = (value - self.loc) / self.scale
        return (gammaln((d + 1) / 2) - gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                - (d + 1) / 2 * jnp.log1p(z * z / d))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.poisson(_key(rng), self.rate, shape).astype(jnp.float32)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return value * jnp.log(self.rate) - self.rate - gammaln(value + 1.0)


# -- transforms (ref python/paddle/distribution/transform.py) ----------------

class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = jnp.asarray(power, jnp.float32)

    def forward(self, x):
        return jnp.power(x, self.power)

    def inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        ldj = 0.0
        for t in self.transforms:
            ldj = ldj + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return ldj


class TransformedDistribution(Distribution):
    """Ref: python/paddle/distribution/transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        self.transform = (transforms if isinstance(transforms, Transform)
                          else ChainTransform(transforms))
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=(), rng=None):
        return self.transform.forward(self.base.rsample(shape, rng=rng))

    def sample(self, shape=(), rng=None):
        return self.transform.forward(self.base.sample(shape, rng=rng))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        return self.base.log_prob(x) - self.transform.forward_log_det_jacobian(x)


# -- KL divergence registry (ref python/paddle/distribution/kl.py) -----------

_KL_REGISTRY: dict[tuple[type, type], callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    # +inf when p's support escapes q's
    contained = (q.low <= p.low) & (p.high <= q.high)
    return jnp.where(contained, kl, jnp.inf)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = p.probs * (jnp.log(jnp.clip(p.probs, 1e-12, None)) -
                   jnp.log(jnp.clip(q.probs, 1e-12, None)))
    b = (1 - p.probs) * (jnp.log(jnp.clip(1 - p.probs, 1e-12, None)) -
                         jnp.log(jnp.clip(1 - q.probs, 1e-12, None)))
    return a + b


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, axis=-1)
    logq = jax.nn.log_softmax(q.logits, axis=-1)
    return jnp.sum(p.probs * (logp - logq), axis=-1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    from jax.scipy.special import betaln, digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return (betaln(a2, b2) - betaln(a1, b1)
            + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
            + (a2 - a1 + b2 - b1) * digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    from jax.scipy.special import digamma, gammaln
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    return (gammaln(a0) - gammaln(b.sum(-1))
            + jnp.sum(gammaln(b) - gammaln(a), axis=-1)
            + jnp.sum((a - b) * (digamma(a) - digamma(a0)[..., None]), axis=-1))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    from jax.scipy.special import digamma, gammaln
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return ((a1 - a2) * digamma(a1) - gammaln(a1) + gammaln(a2)
            + a2 * (jnp.log(b1) - jnp.log(b2)) + a1 * (b2 - b1) / b1)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return jnp.log(p.rate) - jnp.log(q.rate) + r - 1


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    t = jnp.abs(p.loc - q.loc)
    return (jnp.log(q.scale) - jnp.log(p.scale)
            + (p.scale * jnp.exp(-t / p.scale) + t) / q.scale - 1)


# -- round-1 audit additions -------------------------------------------------

class ExponentialFamily(Distribution):
    """Base marker for natural-parameter families (ref exponential_family.py).
    Subclasses may implement ``_natural_parameters``/``_log_normalizer`` for
    the Bregman-divergence entropy path; families here implement entropy
    directly so this is an API-parity base class."""


class Binomial(Distribution):
    """Ref binomial.py: counts of successes in ``total_count`` trials."""

    def __init__(self, total_count, probs):
        self.total_count = jnp.asarray(total_count, jnp.int32)
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        return jax.random.binomial(_key(rng), self.total_count, self.probs,
                                   shape=shape).astype(jnp.float32)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        n = self.total_count.astype(jnp.float32)
        k = value
        log_comb = gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1)
        eps = 1e-12
        return (log_comb + k * jnp.log(self.probs + eps)
                + (n - k) * jnp.log1p(-self.probs + eps))


class Chi2(Gamma):
    """Ref chi2.py — Gamma(df/2, 1/2)."""

    def __init__(self, df):
        self.df = jnp.asarray(df, jnp.float32)
        super().__init__(self.df / 2.0, jnp.asarray(0.5))


class ContinuousBernoulli(Distribution):
    """Ref continuous_bernoulli.py — [0, 1]-supported exponential family
    with pdf C(lam) lam^x (1-lam)^(1-x)."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = jnp.asarray(probs, jnp.float32)
        self.lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        return (self.probs < self.lims[0]) | (self.probs > self.lims[1])

    def _log_norm(self):
        lam = self.probs
        safe = jnp.where(self._outside(), lam, 0.25)
        out = jnp.log((jnp.log1p(-safe) - jnp.log(safe))
                      / (1 - 2 * safe))
        # Taylor around lam=1/2: log 2 + 4/3 (lam - 1/2)^2 + ...
        taylor = jnp.log(2.0) + 4.0 / 3.0 * (lam - 0.5) ** 2
        return jnp.where(self._outside(), out, taylor)

    def log_prob(self, value):
        lam = self.probs
        eps = 1e-12
        return (value * jnp.log(lam + eps)
                + (1 - value) * jnp.log1p(-lam + eps) + self._log_norm())

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(rng), shape, minval=1e-6, maxval=1 - 1e-6)
        lam = jnp.broadcast_to(self.probs, shape)
        safe = jnp.where(self._outside(), lam, 0.25)
        icdf = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return jnp.where(self._outside(), icdf, u)

    @property
    def mean(self):
        lam = self.probs
        safe = jnp.where(self._outside(), lam, 0.25)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return jnp.where(self._outside(), m, 0.5 + (lam - 0.5) / 3.0)


class MultivariateNormal(Distribution):
    """Ref multivariate_normal.py — full-covariance Gaussian; sampling and
    log_prob ride a single cholesky + triangular solve (MXU-friendly)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        if scale_tril is not None:
            self.scale_tril = jnp.asarray(scale_tril, jnp.float32)
        else:
            self.scale_tril = jnp.linalg.cholesky(
                jnp.asarray(covariance_matrix, jnp.float32))
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        return self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2)

    @property
    def variance(self):
        return jnp.sum(self.scale_tril ** 2, axis=-1)

    def rsample(self, shape=(), rng=None):
        shape = _shape(shape) + self.batch_shape + self.event_shape
        z = jax.random.normal(_key(rng), shape, jnp.float32)
        return self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, z)

    def log_prob(self, value):
        d = self.event_shape[0]
        diff = value - self.loc
        y = jax.scipy.linalg.solve_triangular(
            self.scale_tril, diff[..., None], lower=True)[..., 0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), axis=-1)
        return (-0.5 * jnp.sum(y ** 2, axis=-1) - half_logdet
                - 0.5 * d * jnp.log(2 * jnp.pi))

    def entropy(self):
        d = self.event_shape[0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), axis=-1)
        return 0.5 * d * (1 + jnp.log(2 * jnp.pi)) + half_logdet


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    d = p.event_shape[0]
    lp, lq = p.scale_tril, q.scale_tril
    m = jax.scipy.linalg.solve_triangular(lq, lp, lower=True)
    tr = jnp.sum(m ** 2, axis=(-2, -1))
    diff = q.loc - p.loc
    y = jax.scipy.linalg.solve_triangular(lq, diff[..., None], lower=True)[..., 0]
    maha = jnp.sum(y ** 2, axis=-1)
    logdet = (jnp.sum(jnp.log(jnp.diagonal(lq, axis1=-2, axis2=-1)), -1)
              - jnp.sum(jnp.log(jnp.diagonal(lp, axis1=-2, axis2=-1)), -1))
    return 0.5 * (tr + maha - d) + logdet
