"""Signal processing (ref: ``python/paddle/signal.py``): frame, overlap_add,
stft, istft. Framing is a static-shape gather; the FFT is XLA-native — the
whole pipeline jits and differentiates."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu import fft as _fft

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames. axis=-1: [..., seq] -> [..., frame_length,
    n_frames]; axis=0: [seq, ...] -> [frame_length, n_frames, ...]
    (reference layouts, python/paddle/signal.py:frame)."""
    seq_first = axis in (0, -x.ndim)
    if seq_first:
        x = jnp.moveaxis(x, 0, -1)
    seq = x.shape[-1]
    n_frames = 1 + (seq - frame_length) // hop_length
    idx = jnp.arange(n_frames)[:, None] * hop_length + jnp.arange(frame_length)[None, :]
    frames = jnp.swapaxes(x[..., idx], -1, -2)  # [..., frame_length, n_frames]
    if seq_first:
        frames = jnp.moveaxis(jnp.moveaxis(frames, -1, 0), -1, 0)
    return frames


def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame. axis=-1: [..., frame_length, n_frames] -> [..., seq];
    axis=0: [frame_length, n_frames, ...] -> [seq, ...]."""
    seq_first = axis in (0, -x.ndim)
    if seq_first:
        x = jnp.moveaxis(jnp.moveaxis(x, 0, -1), 0, -1)
    frame_length, n_frames = x.shape[-2], x.shape[-1]
    seq = (n_frames - 1) * hop_length + frame_length
    idx = jnp.arange(n_frames)[:, None] * hop_length + jnp.arange(frame_length)[None, :]
    out = jnp.zeros(x.shape[:-2] + (seq,), x.dtype)
    # scatter-add each frame back at its hop offset
    out = out.at[..., idx].add(jnp.swapaxes(x, -1, -2))
    if seq_first:
        out = jnp.moveaxis(out, -1, 0)
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True):
    """[..., seq] -> complex [..., n_freq, n_frames] (ref: paddle.signal.stft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:  # center-pad window to n_fft
        lp = (n_fft - win_length) // 2
        window = jnp.pad(window, (lp, n_fft - win_length - lp))
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = frame(x, n_fft, hop_length)  # [..., n_fft, n_frames]
    frames = frames * window[:, None]
    spec = (_fft.rfft if onesided else _fft.fft)(
        jnp.swapaxes(frames, -1, -2), axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False):
    """Inverse stft with window-envelope normalisation (ref: paddle.signal.istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        window = jnp.pad(window, (lp, n_fft - win_length - lp))
    spec = jnp.swapaxes(x, -1, -2)  # [..., n_frames, n_freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if onesided:
        if return_complex:
            raise ValueError(
                "return_complex=True requires onesided=False (reference "
                "paddle.signal.istft raises for this combination)")
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, axis=-1)
        frames = frames if return_complex else frames.real
    frames = frames * window
    y = overlap_add(jnp.swapaxes(frames, -1, -2), hop_length)
    # normalise by the summed squared window envelope
    wsq = overlap_add(
        jnp.broadcast_to((window ** 2)[:, None],
                         (n_fft, x.shape[-1])), hop_length)
    y = y / jnp.maximum(wsq, 1e-11)
    if center:
        y = y[..., n_fft // 2:]
        end = length if length is not None else y.shape[-1] - n_fft // 2
        y = y[..., :end]
    elif length is not None:
        y = y[..., :length]
    return y
