"""Quantization (ref: ``python/paddle/quantization/`` — QAT fake-quant, PTQ
calibration, quantized inference layers).

TPU-native: int8 matmuls hit the MXU at 2x bf16 throughput via
``lax.dot_general(..., preferred_element_type=jnp.int32)``; fake-quant uses a
straight-through estimator (custom_vjp) so QAT composes with ``jax.grad``.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.module import Module

__all__ = [
    "fake_quant", "quantize_weight", "dequantize", "AbsmaxObserver",
    "FakeQuantLayer", "QuantizedLinear", "quant_linear", "QAT", "PTQ",
]


# -- fake quant with straight-through estimator ------------------------------

@jax.custom_vjp
def fake_quant(x, scale, bits=8):
    qmax = 2.0 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, bits=8):
    return fake_quant(x, scale, bits), (x, scale, bits)


def _fq_bwd(res, g):
    x, scale, bits = res
    qmax = 2.0 ** (bits - 1) - 1
    # STE: pass gradient inside the clip range, zero outside
    inside = (jnp.abs(x / scale) <= 1.0).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quantize_weight(w, bits=8, axis=None):
    """Symmetric int8 quantization; per-channel when axis given.
    Returns (q_int8, scale_fp32)."""
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        scale = jnp.max(jnp.abs(w)).astype(jnp.float32)
    else:
        red = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
        scale = jnp.max(jnp.abs(w), axis=red, keepdims=True).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale * qmax),
                 -qmax - 1, qmax).astype(jnp.int8)
    return q, scale / qmax


def dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


# -- observers / PTQ ---------------------------------------------------------

class AbsmaxObserver:
    """Running absmax calibration (ref: paddle.quantization observers)."""

    def __init__(self, momentum=0.9):
        self.momentum = momentum
        self.absmax = None

    def observe(self, x):
        cur = float(jnp.max(jnp.abs(x)))
        self.absmax = cur if self.absmax is None else \
            self.momentum * self.absmax + (1 - self.momentum) * cur
        return self.absmax

    @property
    def scale(self):
        return max(self.absmax or 1.0, 1e-8)


class FakeQuantLayer(Module):
    """QAT activation fake-quant node; scale is a buffer set by calibration."""

    def __init__(self, bits=8, init_scale=1.0):
        super().__init__()
        self.register_buffer("scale", jnp.asarray(init_scale, jnp.float32))
        self.bits = bits

    def __call__(self, x):
        return fake_quant(x, self.scale, self.bits).astype(x.dtype)


# -- quantized inference layers ----------------------------------------------

class QuantizedLinear(Module):
    """int8-weight linear (ref: paddle.nn.quant.Linear after PTQ).

    Weights stored int8 with per-output-channel scales; activations
    dynamically quantized per-tensor. The matmul runs int8 x int8 -> int32
    on the MXU, then rescales in fp32.
    """

    def __init__(self, weight, bias=None, bits=8):
        super().__init__()
        q, scale = quantize_weight(weight, bits=bits, axis=1)  # [in, out]
        self.register_buffer("qweight", q)
        self.register_buffer("wscale", scale.reshape(1, -1))
        self.bias = bias
        self.bits = bits

    def __call__(self, x):
        qmax = 2.0 ** (self.bits - 1) - 1
        xs = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)),
                                 axis=-1, keepdims=True), 1e-8) / qmax
        qx = jnp.clip(jnp.round(x.astype(jnp.float32) / xs), -qmax - 1,
                      qmax).astype(jnp.int8)
        acc = lax.dot_general(
            qx, self.qweight,
            dimension_numbers=(((qx.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * xs * self.wscale
        if self.bias is not None:
            out = out + self.bias
        return out.astype(x.dtype)


def quant_linear(linear, bits=8):
    """Convert a ``nn.Linear`` into a ``QuantizedLinear`` (PTQ weight-only)."""
    return QuantizedLinear(linear.weight, linear.bias, bits=bits)


# -- high-level entry points (ref paddle.quantization.QAT / PTQ) -------------

@dataclass
class QuantConfig:
    bits: int = 8
    activation: bool = True


class QATLinear(Module):
    """Linear whose weight passes through fake_quant each call (STE grads) —
    the reference's QAT-instrumented layer."""

    def __init__(self, weight, bias=None, bits=8):
        super().__init__()
        self.weight, self.bias, self.bits = weight, bias, bits

    def __call__(self, x):
        # per-output-channel absmax scale (no need to materialise the int8
        # weights during QAT — fake_quant only needs the scale)
        wscale = jnp.maximum(
            jnp.max(jnp.abs(self.weight.astype(jnp.float32)),
                    axis=0, keepdims=True), 1e-8)
        w = fake_quant(self.weight.astype(jnp.float32), wscale,
                       self.bits).astype(x.dtype)
        y = x @ w
        return y + self.bias if self.bias is not None else y


def _replace_linears(model, make):
    import copy

    from paddle_tpu.nn.layers import Linear

    model = copy.deepcopy(model)  # the pass returns a new model (params
    # are immutable jax arrays, so this copies structure, not buffers)

    def convert_item(item):
        if isinstance(item, Linear):
            return make(item)
        if isinstance(item, Module):
            convert_tree(item)
        return item

    def convert_tree(m):
        for name in list(vars(m)):
            sub = getattr(m, name)
            if isinstance(sub, Linear):
                object.__setattr__(m, name, make(sub))
            elif isinstance(sub, Module):
                convert_tree(sub)
            elif isinstance(sub, list):
                for i, item in enumerate(sub):
                    sub[i] = convert_item(item)
            elif isinstance(sub, tuple):
                object.__setattr__(
                    m, name, tuple(convert_item(i) for i in sub))
            elif isinstance(sub, dict):
                for k in list(sub):
                    sub[k] = convert_item(sub[k])
        return m

    return convert_tree(model)


class QAT:
    """Quantization-aware training pass (ref: paddle.quantization.QAT):
    replaces every Linear with a fake-quant-weight QATLinear."""

    def __init__(self, config: QuantConfig = QuantConfig()):
        self.config = config

    def quantize(self, model):
        return _replace_linears(
            model, lambda lin: QATLinear(lin.weight, lin.bias, self.config.bits))


class PTQ:
    """Post-training quantization (ref: paddle.quantization.PTQ): converts
    Linears to int8 QuantizedLinear."""

    def __init__(self, config: QuantConfig = QuantConfig()):
        self.config = config

    def quantize(self, model):
        return _replace_linears(
            model, lambda lin: quant_linear(lin, self.config.bits))



class quanter:
    """Ref paddle.quantization.quanter namespace: fake-quant factories."""

    FakeQuanterWithAbsMax = FakeQuantLayer
