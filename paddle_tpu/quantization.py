"""Quantization (ref: ``python/paddle/quantization/`` — QAT fake-quant, PTQ
calibration, quantized inference layers).

TPU-native: int8 matmuls hit the MXU at 2x bf16 throughput via
``lax.dot_general(..., preferred_element_type=jnp.int32)``; fake-quant uses a
straight-through estimator (custom_vjp) so QAT composes with ``jax.grad``.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.module import Module

__all__ = [
    "fake_quant", "quantize_weight", "dequantize", "AbsmaxObserver",
    "FakeQuantLayer", "QuantizedLinear", "quant_linear", "QAT", "PTQ",
    # weight-only LLM inference (PaddleNLP weight_only_linear / GPTQ parity)
    "QuantizedWeight", "weight_quantize", "weight_only_linear", "wo_matmul",
    "gptq_quantize", "quantize_llama_weights",
]


# -- fake quant with straight-through estimator ------------------------------

@jax.custom_vjp
def fake_quant(x, scale, bits=8):
    qmax = 2.0 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, bits=8):
    return fake_quant(x, scale, bits), (x, scale, bits)


def _fq_bwd(res, g):
    x, scale, bits = res
    qmax = 2.0 ** (bits - 1) - 1
    # STE: pass gradient inside the clip range, zero outside
    inside = (jnp.abs(x / scale) <= 1.0).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quantize_weight(w, bits=8, axis=None):
    """Symmetric int8 quantization; per-channel when axis given.
    Returns (q_int8, scale_fp32)."""
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        scale = jnp.max(jnp.abs(w)).astype(jnp.float32)
    else:
        red = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
        scale = jnp.max(jnp.abs(w), axis=red, keepdims=True).astype(jnp.float32)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale * qmax),
                 -qmax - 1, qmax).astype(jnp.int8)
    return q, scale / qmax


def dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


# -- observers / PTQ ---------------------------------------------------------

class AbsmaxObserver:
    """Running absmax calibration (ref: paddle.quantization observers)."""

    def __init__(self, momentum=0.9):
        self.momentum = momentum
        self.absmax = None

    def observe(self, x):
        cur = float(jnp.max(jnp.abs(x)))
        self.absmax = cur if self.absmax is None else \
            self.momentum * self.absmax + (1 - self.momentum) * cur
        return self.absmax

    @property
    def scale(self):
        return max(self.absmax or 1.0, 1e-8)


class FakeQuantLayer(Module):
    """QAT activation fake-quant node; scale is a buffer set by calibration."""

    def __init__(self, bits=8, init_scale=1.0):
        super().__init__()
        self.register_buffer("scale", jnp.asarray(init_scale, jnp.float32))
        self.bits = bits

    def __call__(self, x):
        return fake_quant(x, self.scale, self.bits).astype(x.dtype)


# -- quantized inference layers ----------------------------------------------

class QuantizedLinear(Module):
    """int8-weight linear (ref: paddle.nn.quant.Linear after PTQ).

    Weights stored int8 with per-output-channel scales; activations
    dynamically quantized per-tensor. The matmul runs int8 x int8 -> int32
    on the MXU, then rescales in fp32.
    """

    def __init__(self, weight, bias=None, bits=8):
        super().__init__()
        q, scale = quantize_weight(weight, bits=bits, axis=1)  # [in, out]
        self.register_buffer("qweight", q)
        self.register_buffer("wscale", scale.reshape(1, -1))
        self.bias = bias
        self.bits = bits

    def __call__(self, x):
        qmax = 2.0 ** (self.bits - 1) - 1
        xs = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)),
                                 axis=-1, keepdims=True), 1e-8) / qmax
        qx = jnp.clip(jnp.round(x.astype(jnp.float32) / xs), -qmax - 1,
                      qmax).astype(jnp.int8)
        acc = lax.dot_general(
            qx, self.qweight,
            dimension_numbers=(((qx.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * xs * self.wscale
        if self.bias is not None:
            out = out + self.bias
        return out.astype(x.dtype)


def quant_linear(linear, bits=8):
    """Convert a ``nn.Linear`` into a ``QuantizedLinear`` (PTQ weight-only)."""
    return QuantizedLinear(linear.weight, linear.bias, bits=bits)


# -- high-level entry points (ref paddle.quantization.QAT / PTQ) -------------

@dataclass
class QuantConfig:
    bits: int = 8
    activation: bool = True


class QATLinear(Module):
    """Linear whose weight passes through fake_quant each call (STE grads) —
    the reference's QAT-instrumented layer."""

    def __init__(self, weight, bias=None, bits=8):
        super().__init__()
        self.weight, self.bias, self.bits = weight, bias, bits

    def __call__(self, x):
        # per-output-channel absmax scale (no need to materialise the int8
        # weights during QAT — fake_quant only needs the scale)
        wscale = jnp.maximum(
            jnp.max(jnp.abs(self.weight.astype(jnp.float32)),
                    axis=0, keepdims=True), 1e-8)
        w = fake_quant(self.weight.astype(jnp.float32), wscale,
                       self.bits).astype(x.dtype)
        y = x @ w
        return y + self.bias if self.bias is not None else y


def _replace_linears(model, make):
    import copy

    from paddle_tpu.nn.layers import Linear

    model = copy.deepcopy(model)  # the pass returns a new model (params
    # are immutable jax arrays, so this copies structure, not buffers)

    def convert_item(item):
        if isinstance(item, Linear):
            return make(item)
        if isinstance(item, Module):
            convert_tree(item)
        return item

    def convert_tree(m):
        for name in list(vars(m)):
            sub = getattr(m, name)
            if isinstance(sub, Linear):
                object.__setattr__(m, name, make(sub))
            elif isinstance(sub, Module):
                convert_tree(sub)
            elif isinstance(sub, list):
                for i, item in enumerate(sub):
                    sub[i] = convert_item(item)
            elif isinstance(sub, tuple):
                object.__setattr__(
                    m, name, tuple(convert_item(i) for i in sub))
            elif isinstance(sub, dict):
                for k in list(sub):
                    sub[k] = convert_item(sub[k])
        return m

    return convert_tree(model)


class QAT:
    """Quantization-aware training pass (ref: paddle.quantization.QAT):
    replaces every Linear with a fake-quant-weight QATLinear."""

    def __init__(self, config: QuantConfig = QuantConfig()):
        self.config = config

    def quantize(self, model):
        return _replace_linears(
            model, lambda lin: QATLinear(lin.weight, lin.bias, self.config.bits))


class PTQ:
    """Post-training quantization (ref: paddle.quantization.PTQ): converts
    Linears to int8 QuantizedLinear."""

    def __init__(self, config: QuantConfig = QuantConfig()):
        self.config = config

    def quantize(self, model):
        return _replace_linears(
            model, lambda lin: quant_linear(lin, self.config.bits))



class quanter:
    """Ref paddle.quantization.quanter namespace: fake-quant factories."""

    FakeQuanterWithAbsMax = FakeQuantLayer


# -- weight-only LLM inference quantization ----------------------------------
# (ref capability: PaddleNLP ``paddle.nn.quant.weight_only_linear`` /
# ``weight_quantize`` + the GPTQ algorithm from the llm toolchain)

class QuantizedWeight:
    """int8/int4 weight + per-out-channel scale, as a pytree.

    Layout: original weight [K, N] (in, out). int8 stores q as [K, N] int8;
    int4 packs two 4-bit values per byte ALONG K -> [ceil(K/2), N] int8
    (low nibble = even k, high nibble = odd k). The matmul dequantizes
    per-column AFTER the int8->compute-dtype cast, so HBM holds 1 (or 0.5)
    byte/param — the decode-bandwidth win weight-only quantization exists
    for."""

    def __init__(self, q, scale, bits: int, k: int):
        self.q = q
        self.scale = scale          # [1, N] fp32
        self.bits = int(bits)
        self.k = int(k)

    def tree_flatten(self):
        return (self.q, self.scale), (self.bits, self.k)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0], aux[1])

    @property
    def shape(self):
        return (self.k, self.q.shape[-1])

    def nbytes(self):
        return self.q.size * self.q.dtype.itemsize + self.scale.size * 4

    def unpack(self):
        """int8 [K, N] values (sign-extended nibbles for int4)."""
        if self.bits == 8:
            return self.q
        packed = self.q
        low = jnp.left_shift(packed, 4)
        low = jnp.right_shift(low, 4)          # arithmetic: sign-extends
        high = jnp.right_shift(packed, 4)
        out = jnp.stack([low, high], axis=1).reshape(-1, packed.shape[-1])
        return out[: self.k]

    def dequantize(self, dtype=jnp.float32):
        return (self.unpack().astype(jnp.float32) * self.scale).astype(dtype)


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda t: t.tree_flatten(),
    QuantizedWeight.tree_unflatten)


def weight_quantize(w, algo: str = "weight_only_int8"):
    """RTN per-out-channel symmetric quantization (ref weight_quantize)."""
    bits = {"weight_only_int8": 8, "weight_only_int4": 4}[algo]
    k, n = w.shape
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0,
                                keepdims=True), 1e-8) / qmax
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -qmax, qmax).astype(jnp.int8)
    return QuantizedWeight(_pack(q, bits), scale, bits, k)


def _pack(q, bits):
    if bits == 8:
        return q
    if q.shape[0] % 2:
        q = jnp.concatenate([q, jnp.zeros((1, q.shape[1]), q.dtype)], axis=0)
    low = q[0::2]
    high = q[1::2]
    return ((high.astype(jnp.int32) << 4)
            | (low.astype(jnp.int32) & 0xF)).astype(jnp.int8)


def weight_only_linear(x, qw: QuantizedWeight, bias=None):
    """x @ dequant(qw) with the dequant fused into the matmul epilogue:
    y = (x @ q) * scale — int8/int4 weights stream from HBM, the
    per-out-channel scale applies to the [.., N] result (ref
    weight_only_linear)."""
    q = qw.unpack().astype(x.dtype)
    y = (x @ q) * qw.scale.astype(x.dtype)[0]
    return y if bias is None else y + bias


def wo_matmul(x, w):
    """Dispatch: plain matmul or weight-only quantized matmul."""
    if isinstance(w, QuantizedWeight):
        return weight_only_linear(x, w)
    return x @ w


def gptq_quantize(w, calib_x, bits: int = 4, percdamp: float = 0.01):
    """GPTQ: error-compensated rounding using the calibration Hessian
    (H = 2 X^T X). Quantizes in-dim columns in order, propagating each
    column's rounding error into the not-yet-quantized columns through the
    inverse-Hessian Cholesky factor. Host-side (offline), numpy float64.

    w: [K, N] (in, out); calib_x: [M, K] activations feeding this matmul.
    Returns QuantizedWeight with the SAME layout/scales as RTN — only the
    rounding decisions differ (strictly better reconstruction on the
    calibration distribution).
    """
    import numpy as np

    W = np.asarray(w, np.float64).T.copy()          # [N, K] rows = out
    X = np.asarray(calib_x, np.float64)
    n, k = W.shape
    qmax = 2.0 ** (bits - 1) - 1
    scale = np.maximum(np.abs(W).max(axis=1, keepdims=True), 1e-8) / qmax

    H = 2.0 * (X.T @ X)
    damp = percdamp * float(np.mean(np.diag(H)) or 1.0)
    H[np.diag_indices(k)] += damp
    # upper Cholesky factor of H^-1 with Hinv = U^T U — the GPTQ recursion
    # divides by U[j, j] and feeds errors forward along row U[j, j+1:]
    Hinv = np.linalg.inv(H)
    U = np.linalg.cholesky(Hinv).T

    Q = np.zeros_like(W)
    for j in range(k):
        wc = W[:, j]
        qc = np.clip(np.round(wc / scale[:, 0]), -qmax, qmax)
        Q[:, j] = qc
        err = (wc - qc * scale[:, 0]) / U[j, j]
        if j + 1 < k:
            W[:, j + 1:] -= np.outer(err, U[j, j + 1:])
    q = jnp.asarray(Q.T, jnp.int8)                  # back to [K, N]
    return QuantizedWeight(_pack(q, bits), jnp.asarray(scale.T, jnp.float32),
                           bits, k)


def quantize_llama_weights(model, algo: str = "weight_only_int8",
                           calib_ids=None, percdamp: float = 0.01):
    """Weight-only quantize a LLaMA-family model IN PLACE for inference:
    the qkv/o/gate_up/down projections (and untied lm_head) become
    ``QuantizedWeight``s; the forward/decode paths dispatch through
    ``wo_matmul``. ``algo``: weight_only_int8 | weight_only_int4 |
    gptq_int8 | gptq_int4 (gptq needs ``calib_ids`` [B, S] to build
    per-matmul Hessians from a capture forward)."""
    gptq = algo.startswith("gptq")
    if any(getattr(lyr.self_attn, "fp8_meta", None) is not None
           for lyr in model.model.layers):
        raise ValueError(
            "weight-only quantization and the fp8 training path are "
            "mutually exclusive (fp8_matmul cannot consume QuantizedWeight);"
            " rebuild the model with fp8=False for quantized inference")
    bits = 4 if algo.endswith("int4") else 8
    rtn_algo = f"weight_only_int{bits}"
    calib = None
    if gptq:
        if calib_ids is None:
            raise ValueError("gptq quantization needs calib_ids")
        calib = _capture_calib(model, calib_ids)

    for li, lyr in enumerate(model.model.layers):
        att, mlp = lyr.self_attn, lyr.mlp
        if gptq:
            c = calib[li]
            att.qkv_proj = gptq_quantize(att.qkv_proj, c["qkv"], bits,
                                         percdamp)
            att.o_proj = gptq_quantize(att.o_proj, c["o"], bits, percdamp)
            mlp.gate_up_proj = gptq_quantize(mlp.gate_up_proj, c["gate_up"],
                                             bits, percdamp)
            mlp.down_proj = gptq_quantize(mlp.down_proj, c["down"], bits,
                                          percdamp)
        else:
            att.qkv_proj = weight_quantize(att.qkv_proj, rtn_algo)
            att.o_proj = weight_quantize(att.o_proj, rtn_algo)
            mlp.gate_up_proj = weight_quantize(mlp.gate_up_proj, rtn_algo)
            mlp.down_proj = weight_quantize(mlp.down_proj, rtn_algo)
    if getattr(model, "lm_head", None) is not None:
        if gptq:
            model.lm_head = gptq_quantize(model.lm_head, calib[-1]["head"],
                                          bits, percdamp)
        else:
            model.lm_head = weight_quantize(model.lm_head, rtn_algo)
    return model


def _capture_calib(model, ids):
    """One forward pass recording the input activations of each projection
    matmul per decoder layer (flattened [B*S, K]); the last layer's record
    also carries the lm_head input (post final-norm hidden states)."""
    import numpy as np

    import paddle_tpu.ops.attention as A

    cfg = model.cfg
    x = jnp.take(model.model.embed_tokens, ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    cos, sin = A.rope_cos_sin(ids.shape[1], d, base=cfg.rope_theta,
                              scaling=getattr(cfg, "rope_scaling", None),
                              max_position_embeddings=getattr(
                                  cfg, "max_position_embeddings", None))
    out = []
    for lyr in model.model.layers:
        att, mlp = lyr.self_attn, lyr.mlp
        rec = {}
        h = lyr.input_layernorm(x)
        rec["qkv"] = np.asarray(h.reshape(-1, h.shape[-1]), np.float32)
        # ONE attention pass, honouring the model's sliding window, both
        # records the o-proj input and produces the layer's output
        b, s, _ = h.shape
        qkv = wo_matmul(h, att.qkv_proj)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, kk, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        kk = A.apply_rope(kk.reshape(b, s, nkv, hd), cos, sin)
        ctx = A.scaled_dot_product_attention(
            q, kk, v.reshape(b, s, nkv, hd), is_causal=True,
            window=getattr(att, "window", None))
        ctx = ctx.reshape(b, s, nh * hd)
        rec["o"] = np.asarray(ctx.reshape(-1, nh * hd), np.float32)
        x = x + wo_matmul(ctx, att.o_proj)
        h2 = lyr.post_attention_layernorm(x)
        rec["gate_up"] = np.asarray(h2.reshape(-1, h2.shape[-1]), np.float32)
        gu = wo_matmul(h2, mlp.gate_up_proj)
        gate, up = jnp.split(gu, 2, axis=-1)
        act = jax.nn.silu(gate) * up
        rec["down"] = np.asarray(act.reshape(-1, act.shape[-1]), np.float32)
        x = x + wo_matmul(act, mlp.down_proj)
        out.append(rec)
    final = model.model.norm(x)
    out[-1]["head"] = np.asarray(final.reshape(-1, final.shape[-1]),
                                 np.float32)
    return out
