import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import time, json, sys
import jax, jax.numpy as jnp, numpy as np
import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, num_flops_per_token
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state

PEAK = 197e12

def run(tag, remat, scan, batch=4, seq=2048, iters=10):
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                      num_hidden_layers=12, num_attention_heads=16,
                      num_key_value_heads=16, max_position_embeddings=2048,
                      dtype=jnp.bfloat16, remat=remat, scan_layers=scan)
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-4, weight_decay=0.1,
                          grad_clip=opt.ClipGradByGlobalNorm(1.0), multi_precision=True)
    state = init_state(model, optimizer)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.concatenate([ids[:, 1:], -100*jnp.ones((batch,1), ids.dtype)], axis=1)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
    try:
        state, l = step(state, ids, labels); float(jax.device_get(l))
        state, l = step(state, ids, labels); float(jax.device_get(l))
        t0 = time.perf_counter()
        for _ in range(iters):
            state, l = step(state, ids, labels)
        float(jax.device_get(l))
        dt = (time.perf_counter()-t0)/iters
        mfu = batch*seq*num_flops_per_token(cfg, seq)/dt/PEAK
        print(json.dumps({"tag": tag, "step_ms": round(dt*1e3,1), "mfu": round(mfu,4)}), flush=True)
    except Exception as e:
        print(json.dumps({"tag": tag, "error": str(e)[:150]}), flush=True)

for arg in sys.argv[1:]:
    if arg == "noremat_scan":
        run(arg, False, True)
    elif arg == "noremat_unroll":
        run(arg, False, False)
    elif arg == "remat_unroll":
        run(arg, True, False)
    elif arg == "noremat_scan_b8":
        run(arg, False, True, batch=8)
