#!/bin/bash
# Self-harvesting TPU-tunnel watcher (VERDICT r3 item #1).
#
# Replaces the passive probe loop: every POLL seconds, probe the tunnel with
# a real matmul; the moment a probe succeeds, execute the run-FIRST list
# unattended, in order, each step with its own timeout and a persistent
# done-marker so an interrupted window resumes where it left off:
#
#   1. benchmarks/tpu_probe.py      — Mosaic validation of every Pallas leg
#   2. benchmarks/_perf_banded.py   — banded-grid experiment matrix
#   3. python bench.py              — headline MFU (artifact replayed by
#                                     bench.py if the tunnel later dies)
#   4. benchmarks/_perf_attn.py     — flash-vs-XLA microbench
#   5. benchmarks/_perf_sweep2.py   — remat/scan step sweeps
#
# Known tunnel hazards handled (NOTES_ROUND4): a hung client wedges the
# tunnel for later processes -> stragglers are killed before every probe;
# block_until_ready is a no-op over the tunnel -> the scripts sync via
# float() fetch themselves.  Artifacts land in benchmarks/artifacts/.
set -u
REPO=/root/repo
ART=$REPO/benchmarks/artifacts
STATE=$ART/state
LOG=$REPO/.tpu_watch.log
HLOG=$ART/harvest.log
POLL=${POLL:-120}
mkdir -p "$STATE"

# single-instance guard
PIDFILE=$ART/harvest.pid
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
  echo "harvester already running (pid $(cat "$PIDFILE"))" >&2
  exit 0
fi
echo $$ > "$PIDFILE"

note() { echo "$(date +%F' '%H:%M:%S) $*" >> "$HLOG"; }

kill_stragglers() {
  # any leftover python running our bench/probe scripts can wedge the tunnel
  for pat in tpu_probe.py _perf_banded.py _perf_attn.py _perf_sweep2.py \
             _perf_breakdown.py _perf_experiment.py "bench.py"; do
    pgrep -f "python.*$pat" | while read -r p; do
      [ "$p" != "$$" ] && kill -9 "$p" 2>/dev/null
    done
  done
}

probe() {
  out=$(timeout 75 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256,256)); v = float(jnp.sum(x@x))
print('UP', d[0].platform, d[0])" 2>/dev/null | tail -1)
  case "$out" in
    UP\ tpu*) echo "UP $out"; return 0 ;;
    UP*)      echo "NONTPU $out"; return 1 ;;
    *)        echo "DOWN"; return 1 ;;
  esac
}

# run_step <name> <timeout_s> <max_attempts> <cmd...>
run_step() {
  name=$1; tmo=$2; maxtry=$3; shift 3
  [ -f "$STATE/$name.done" ] && return 0
  tries=$(cat "$STATE/$name.attempts" 2>/dev/null || echo 0)
  if [ "$tries" -ge "$maxtry" ]; then return 0; fi
  echo $((tries + 1)) > "$STATE/$name.attempts"
  ts=$(date +%m%d_%H%M%S)
  out="$ART/${name}_${ts}.log"
  note "step $name attempt $((tries + 1)) -> $out"
  ( cd "$REPO" && timeout "$tmo" "$@" ) > "$out" 2>&1
  rc=$?
  note "step $name rc=$rc"
  if [ "$rc" -eq 0 ]; then
    touch "$STATE/$name.done"
    cp "$out" "$ART/${name}_LAST_GOOD.log"
  fi
  return "$rc"
}

harvest() {
  # steps in the VERDICT's priority order; a failing step never blocks
  # the later ones. Between steps, re-probe cheaply: if the tunnel died
  # mid-window, bail out and resume at the next UP.
  run_step probe_quick 420 4 python benchmarks/tpu_probe.py --quick
  probe >/dev/null || return
  run_step probe_full 900 4 python benchmarks/tpu_probe.py
  probe >/dev/null || return
  run_step banded 1200 3 python benchmarks/_perf_banded.py
  probe >/dev/null || return
  if [ ! -f "$STATE/bench.done" ]; then
    tries=$(cat "$STATE/bench.attempts" 2>/dev/null || echo 0)
    if [ "$tries" -lt 4 ]; then
      echo $((tries + 1)) > "$STATE/bench.attempts"
      ts=$(date +%m%d_%H%M%S)
      out="$ART/bench_${ts}.log"
      note "step bench attempt $((tries + 1)) -> $out"
      ( cd "$REPO" && timeout 2400 python bench.py ) > "$out" 2>&1
      # success = last line parses as JSON without "degraded": true
      if tail -1 "$out" | python -c "
import json, sys
d = json.loads(sys.stdin.readline())
sys.exit(1 if d.get('degraded') else 0)" 2>/dev/null; then
        tail -1 "$out" > "$ART/bench_onchip.json"
        touch "$STATE/bench.done"
        note "step bench SUCCESS (on-chip result saved)"
      else
        note "step bench degraded/failed"
      fi
    fi
  fi
  probe >/dev/null || return
  run_step perf_attn 900 3 python benchmarks/_perf_attn.py
  probe >/dev/null || return
  run_step perf_sweep 1800 2 python benchmarks/_perf_sweep2.py \
    noremat_scan noremat_unroll remat_unroll noremat_scan_b8
}

note "harvester start (pid $$, poll ${POLL}s)"
while true; do
  ts=$(date +%H:%M:%S)
  kill_stragglers
  if st=$(probe); then
    echo "$ts $st" >> "$LOG"
    if ls "$STATE"/*.done >/dev/null 2>&1 \
       && [ -f "$STATE/probe_full.done" ] && [ -f "$STATE/banded.done" ] \
       && [ -f "$STATE/bench.done" ] && [ -f "$STATE/perf_attn.done" ] \
       && [ -f "$STATE/perf_sweep.done" ]; then
      : # everything harvested; stay as a plain watcher
    else
      note "tunnel UP -> harvesting"
      harvest
      note "harvest pass done"
    fi
  else
    echo "$ts $st" >> "$LOG"
  fi
  sleep "$POLL"
done
