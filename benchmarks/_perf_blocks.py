"""On-chip flash-attention block-size sweep (VERDICT r3 weak #4: the
DEFAULT_BLOCK_Q/K = 128 were chosen a priori).

Times causal flash fwd and fwd+bwd at the headline-bench attention shape
(B4 S2048 H16 D128) over a (block_q, block_k) grid. 128x128 measured only
~7 TFLOP/s (3.5% of v5e bf16 peak) — a single 128^3 MXU issue per grid
step can't saturate; bigger tiles amortise the per-step overhead.

Usage: python benchmarks/_perf_blocks.py [--bwd] [--quick]
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas.flash_attention import flash_attention

B, S, H, D = 4, 2048, 16, 128
ITERS = 20
FLOPS_FWD = 2 * 2 * B * H * S * S * D * 0.5  # causal: half the tiles


def timeit(f, *a):
    r = f(*a)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), r)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        r = f(*a)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), r)
    return (time.perf_counter() - t0) / ITERS


def main():
    do_bwd = "--bwd" in sys.argv
    quick = "--quick" in sys.argv
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)

    combos = [(128, 128), (256, 256), (256, 512), (512, 512),
              (512, 1024), (256, 1024), (512, 256), (1024, 1024)]
    if quick:
        combos = [(128, 128), (256, 512), (512, 512)]

    results = []
    for bq, bk in combos:
        row = {"bq": bq, "bk": bk}
        try:
            f = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk))
            t = timeit(f, q, k, v)
            row["fwd_ms"] = round(t * 1e3, 3)
            row["fwd_tflops"] = round(FLOPS_FWD / t / 1e12, 1)
        except Exception as e:  # noqa: BLE001
            row["fwd_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        if do_bwd and "fwd_ms" in row:
            try:
                g = jax.jit(jax.grad(lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                    flash_attention(q, k, v, causal=True, block_q=bq,
                                    block_k=bk).astype(jnp.float32)),
                    argnums=(0, 1, 2)))
                t = timeit(g, q, k, v)
                row["fwdbwd_ms"] = round(t * 1e3, 3)
            except Exception as e:  # noqa: BLE001
                row["bwd_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps(row), flush=True)
        results.append(row)

    ok = [r for r in results if "fwd_ms" in r]
    if ok:
        best = min(ok, key=lambda r: r.get("fwdbwd_ms", r["fwd_ms"]))
        print("BEST: " + json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
