import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
"""Perf sweep on the real chip: remat policy x batch size."""
import time, json, sys
import jax, jax.numpy as jnp, numpy as np

import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, num_flops_per_token
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state

PEAK = 197e12

def run(policy, batch, seq=2048, iters=10):
    import paddle_tpu.models.llama as llama_mod
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                      num_hidden_layers=12, num_attention_heads=16,
                      num_key_value_heads=16, max_position_embeddings=2048,
                      dtype=jnp.bfloat16, remat=True, scan_layers=True)
    # monkeypatch the checkpoint policy for the experiment
    orig_ckpt = jax.checkpoint
    if policy is not None:
        import functools
        def ckpt(f, **kw):
            kw.pop("policy", None)
            return orig_ckpt(f, policy=policy, **kw)
        llama_mod.jax.checkpoint = ckpt
    try:
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=3e-4, weight_decay=0.1,
                              grad_clip=opt.ClipGradByGlobalNorm(1.0),
                              multi_precision=True)
        state = init_state(model, optimizer)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)))
        labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((batch, 1), ids.dtype)], axis=1)
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
        state, loss = step(state, ids, labels)
        float(jax.device_get(loss))
        state, loss = step(state, ids, labels)
        float(jax.device_get(loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step(state, ids, labels)
        float(jax.device_get(loss))
        dt = (time.perf_counter() - t0) / iters
        tps = batch * seq / dt
        mfu = tps * num_flops_per_token(cfg, seq) / PEAK
        print(json.dumps({"policy": str(policy), "batch": batch,
                          "step_ms": round(dt*1e3,1), "tps": round(tps,1),
                          "mfu": round(mfu,4)}), flush=True)
    except Exception as e:
        print(json.dumps({"policy": str(policy), "batch": batch, "error": str(e)[:200]}), flush=True)
    finally:
        llama_mod.jax.checkpoint = orig_ckpt


which = sys.argv[1]
pol = jax.checkpoint_policies
if which == "baseline":
    run(None, 4)
elif which == "dots":
    run(pol.dots_with_no_batch_dims_saveable, 4)
elif which == "dots8":
    run(pol.dots_with_no_batch_dims_saveable, 8)
elif which == "base8":
    run(None, 8)
