import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import time, json
import jax, jax.numpy as jnp, numpy as np
import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, num_flops_per_token
from paddle_tpu.core.module import partition_trainable, combine, value_and_grad
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state

PEAK = 197e12
cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                  num_hidden_layers=12, num_attention_heads=16,
                  num_key_value_heads=16, max_position_embeddings=2048,
                  dtype=jnp.bfloat16, remat=True, scan_layers=True)
batch, seq, iters = 4, 2048, 10
pt.seed(0)
model = LlamaForCausalLM(cfg)
rs = np.random.RandomState(0)
ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)))
labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((batch, 1), ids.dtype)], axis=1)

def timeit(f, *args, n=iters):
    out = f(*args); jax.device_get(jax.tree_util.tree_leaves(out)[0].sum() if hasattr(jax.tree_util.tree_leaves(out)[0], 'sum') else out)
    out = f(*args); jax.device_get(jax.tree_util.tree_leaves(out)[0].sum())
    t0 = time.perf_counter()
    r = None
    for _ in range(n):
        r = f(*args)
    jax.device_get(jax.tree_util.tree_leaves(r)[0].sum())
    return (time.perf_counter() - t0) / n

fwd = jax.jit(lambda m, i: m(i))
t_fwd = timeit(fwd, model, ids)

loss_j = jax.jit(lambda m, i, l: m.loss(i, l))
t_loss = timeit(loss_j, model, ids, labels)

grad_j = jax.jit(lambda m, i, l: value_and_grad(lambda mm, ii, ll: mm.loss(ii, ll))(m, i, l))
t_grad = timeit(grad_j, model, ids, labels)

optimizer = opt.AdamW(learning_rate=3e-4, weight_decay=0.1,
                      grad_clip=opt.ClipGradByGlobalNorm(1.0), multi_precision=True)
state = init_state(model, optimizer)
step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
t_step = None
s2 = state
s2, l = step(s2, ids, labels); float(jax.device_get(l))
s2, l = step(s2, ids, labels); float(jax.device_get(l))
t0 = time.perf_counter()
for _ in range(iters):
    s2, l = step(s2, ids, labels)
float(jax.device_get(l))
t_step = (time.perf_counter() - t0) / iters

fpt = num_flops_per_token(cfg, seq)
tok = batch * seq
print(json.dumps({
    "fwd_ms": round(t_fwd*1e3,1), "loss_ms": round(t_loss*1e3,1),
    "grad_ms": round(t_grad*1e3,1), "step_ms": round(t_step*1e3,1),
    "fwd_mfu_vs_third": round(tok*(fpt/3)/t_fwd/PEAK, 3),
    "grad_mfu": round(tok*fpt/t_grad/PEAK, 3),
    "step_mfu": round(tok*fpt/t_step/PEAK, 3),
}))
