"""Attention microbench: pallas flash vs xla attention, fwd and fwd+bwd.

NB: q/k/v must be ARGUMENTS of the jitted fns — closed-over arrays become
HLO constants, which the axon tunnel serializes into the compile request.
"""
import os, sys, time, json
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from paddle_tpu.ops import attention as A
from paddle_tpu.ops.pallas.flash_attention import flash_attention

B, S, H, D = 4, 2048, 16, 128
rs = np.random.RandomState(0)
q = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
k = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
v = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)

def timeit(f, *a, n=20):
    r = f(*a); float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
    r = f(*a); float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*a)
    float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
    return (time.perf_counter() - t0) / n

flash_f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
xla_f = jax.jit(lambda q, k, v: A.xla_attention(q, k, v, is_causal=True))
t_flash = timeit(flash_f, q, k, v)
print("flash fwd", t_flash, flush=True)
t_xla = timeit(xla_f, q, k, v)
print("xla fwd", t_xla, flush=True)

g_flash = jax.jit(jax.grad(
    lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32))))
g_xla = jax.jit(jax.grad(
    lambda q, k, v: jnp.sum(A.xla_attention(q, k, v, is_causal=True).astype(jnp.float32))))
t_gflash = timeit(g_flash, q, k, v)
print("flash bwd", t_gflash, flush=True)
t_gxla = timeit(g_xla, q, k, v)

flops = 2 * 2 * B * H * S * S * D * 0.5
print(json.dumps({
    "flash_fwd_ms": round(t_flash*1e3,2), "xla_fwd_ms": round(t_xla*1e3,2),
    "flash_fwdbwd_ms": round(t_gflash*1e3,2), "xla_fwdbwd_ms": round(t_gxla*1e3,2),
    "flash_fwd_tflops": round(flops/t_flash/1e12,1),
    "xla_fwd_tflops": round(flops/t_xla/1e12,1),
}))
