"""One-command on-chip validation of every Pallas path added while the
TPU tunnel was down. Run me FIRST when the tunnel returns:

    python benchmarks/tpu_probe.py            # all probes
    python benchmarks/tpu_probe.py --quick    # small shapes only

Each probe compares the Mosaic-lowered kernel against the XLA reference at
bf16 tolerance and prints one PASS/FAIL line; exit code is the number of
failures. Interpret-mode CPU tests do NOT cover lowering/tiling, which is
exactly what this script exists to catch (see .claude/skills/verify).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

FAILURES = []


def probe(name):
    def deco(fn):
        def run(*a, **k):
            t0 = time.perf_counter()
            try:
                fn(*a, **k)
                dt = time.perf_counter() - t0
                print(f"PASS {name} ({dt:.1f}s)", flush=True)
            except Exception as e:  # noqa: BLE001 — report and continue
                FAILURES.append(name)
                print(f"FAIL {name}: {type(e).__name__}: {e}", flush=True)
        return run
    return deco


def _qkv(rs, b, s, h, d, hkv=None, dtype=jnp.bfloat16):
    hkv = hkv or h
    q = jnp.asarray(rs.randn(b, s, h, d), dtype)
    k = jnp.asarray(rs.randn(b, s, hkv, d), dtype)
    v = jnp.asarray(rs.randn(b, s, hkv, d), dtype)
    return q, k, v


def _close(got, ref, frac=0.03, name="out"):
    """bf16 kernel-vs-XLA comparison scaled to the reference magnitude."""
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    bound = frac * np.abs(ref).max() + 2e-2
    diff = np.abs(got - ref).max()
    assert diff <= bound, f"{name}: maxdiff {diff} > {bound}"


@probe("flash causal fwd+bwd S=2048")
def flash_causal(s=2048):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs, 1, s, 4, 128)
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=False)
    _close(got, ref)
    g_ref = jax.grad(lambda q: jnp.sum(
        xla_attention(q, k, v, is_causal=True).astype(jnp.float32) ** 2))(q)
    g_got = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2))(q)
    _close(g_got, g_ref)


@probe("flash banded window S=4096 w=1024 (fwd+bwd + timing vs full)")
def flash_banded(s=4096, w=1024):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs, 1, s, 2, 128)
    ref = xla_attention(q, k, v, is_causal=True, window=w)
    got = flash_attention(q, k, v, causal=True, window=w, interpret=False)
    _close(got, ref)
    g = jax.grad(lambda k: jnp.sum(flash_attention(
        q, k, v, causal=True, window=w).astype(jnp.float32) ** 2))(k)
    assert np.all(np.isfinite(np.asarray(g, np.float32)))

    # the banded grid must beat full-causal on wall clock at w << S
    def timeit(f):
        # warmup MUST sync via a host fetch: block_until_ready is a no-op
        # over the axon tunnel, and without the fetch the banded variant's
        # Mosaic compile lands inside the timed loop (the r3 "6.5x slower"
        # and r4 "653ms" findings were THIS, not kernel slowness —
        # benchmarks/_perf_banded2.py times the same kernels at 1.7-1.8x
        # FASTER than full causal once warmed correctly)
        float(jnp.sum(f().astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(10):
            out = f()
        float(jnp.sum(out.astype(jnp.float32)))  # tunnel-safe sync
        return (time.perf_counter() - t0) / 10

    # time at compute-dominated shapes (B4/H8): at B1/H2 both variants sit
    # on the ~3.4ms tunnel dispatch floor and the comparison is noise
    st, wt = (2048, 512) if s < 4096 else (s, w)
    rs2 = np.random.RandomState(9)
    qt, kt, vt = _qkv(rs2, 4, st, 8, 128)
    t_band = timeit(jax.jit(lambda: flash_attention(qt, kt, vt, causal=True,
                                                    window=wt)))
    t_full = timeit(jax.jit(lambda: flash_attention(qt, kt, vt, causal=True)))
    print(f"   banded {t_band*1e3:.2f}ms vs full {t_full*1e3:.2f}ms "
          f"(B4 H8 S{st} w{wt})")
    assert t_band < t_full, "banded grid is not faster than full causal"

    if s >= 4096:
        # round-3 done-criterion: S=8k / W=4k END-TO-END win. At w=S/2 the
        # banded FLOPs are ~75% of causal (S*w - w^2/2 vs S^2/2), so the
        # margin is structurally thin — this leg catches any per-grid-step
        # overhead of the banded index maps that the w<<S leg would hide.
        rs3 = np.random.RandomState(10)
        q8, k8, v8 = _qkv(rs3, 2, 8192, 8, 128)
        t_b8 = timeit(jax.jit(lambda: flash_attention(q8, k8, v8, causal=True,
                                                      window=4096)))
        t_f8 = timeit(jax.jit(lambda: flash_attention(q8, k8, v8,
                                                      causal=True)))
        print(f"   banded {t_b8*1e3:.2f}ms vs full {t_f8*1e3:.2f}ms "
              f"(B2 H8 S8192 w4096)")
        assert t_b8 < t_f8, "banded not faster end-to-end at S=8k/W=4k"


@probe("flash GQA kv_rep=4 zero-copy index maps")
def flash_gqa(s=1024):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, 2, s, 8, 128, hkv=2)
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=False)
    _close(got, ref)


@probe("flash decode sq!=sk alignment")
def flash_decode(sk=1024, sq=128):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, sq, 4, 128), jnp.bfloat16)
    k = jnp.asarray(rs.randn(1, sk, 4, 128), jnp.bfloat16)
    v = jnp.asarray(rs.randn(1, sk, 4, 128), jnp.bfloat16)
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=False)
    _close(got, ref)


@probe("flash fused alibi_slopes (in-tile bias)")
def flash_alibi(s=1024):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(8)
    q, k, v = _qkv(rs, 2, s, 8, 128, hkv=2)   # with GQA
    slopes = jnp.asarray(2.0 ** (-np.arange(1, 9)), jnp.float32)
    ref = xla_attention(q, k, v, is_causal=True, alibi_slopes=slopes)
    got = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          interpret=False)
    _close(got, ref)


@probe("flash varlen kv_lens (padded batch)")
def flash_varlen(s=1024):
    from paddle_tpu.ops.attention import xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs, 3, s, 4, 128)
    lens = jnp.asarray([s, s // 2, 17], jnp.int32)
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    ref = xla_attention(q, k, v, attn_mask=pad, is_causal=True)
    got = flash_attention(q, k, v, causal=True, kv_lens=lens,
                          interpret=False)
    valid = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]
    _close(got * valid, ref * valid)


@probe("paged decode kernel vs gather reference")
def paged_kernel():
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas, paged_decode_attention_xla)
    rs = np.random.RandomState(5)
    b, h, hkv, d, nb, bs, mb = 4, 8, 2, 128, 64, 16, 8
    q = jnp.asarray(rs.randn(b, h, d), jnp.bfloat16)
    k_pool = jnp.asarray(rs.randn(nb, bs, hkv, d), jnp.bfloat16)
    v_pool = jnp.asarray(rs.randn(nb, bs, hkv, d), jnp.bfloat16)
    tables = jnp.asarray(rs.choice(nb, (b, mb), replace=False).reshape(b, mb),
                         jnp.int32)
    lens = jnp.asarray([mb * bs, 70, 16, 3], jnp.int32)
    ref = paged_decode_attention_xla(q, k_pool, v_pool, tables, lens)
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables, lens,
                                        interpret=False)
    _close(got, ref)


@probe("fused rope + rms_norm kernels")
def fused_small():
    from paddle_tpu.ops import fused_rms_norm
    from paddle_tpu.ops.attention import rope_cos_sin, apply_rope
    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(2, 512, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.bfloat16)
    y = fused_rms_norm(x, w, 1e-5)
    ref = (x.astype(jnp.float32)
           / jnp.sqrt(jnp.mean(x.astype(jnp.float32) ** 2, -1,
                               keepdims=True) + 1e-5))
    _close(y, ref)
    q = jnp.asarray(rs.randn(1, 512, 8, 128), jnp.bfloat16)
    cos, sin = rope_cos_sin(512, 128)
    assert np.all(np.isfinite(np.asarray(apply_rope(q, cos, sin),
                                         np.float32)))


def main():
    quick = "--quick" in sys.argv
    if jax.default_backend() != "tpu":
        print(f"WARNING: backend is {jax.default_backend()!r}, not tpu — "
              "this script validates MOSAIC LOWERING and should run "
              "on-chip", flush=True)
    flash_causal(512 if quick else 2048)
    flash_banded(*( (1024, 256) if quick else (4096, 1024)))
    flash_gqa(512 if quick else 1024)
    flash_decode(*((512, 128) if quick else (1024, 128)))
    flash_varlen(512 if quick else 1024)
    flash_alibi(512 if quick else 1024)
    paged_kernel()
    fused_small()
    print(f"\n{len(FAILURES)} failure(s)" + (f": {FAILURES}" if FAILURES
                                             else " — all kernels verified"))
    return len(FAILURES)


if __name__ == "__main__":
    raise SystemExit(main())
