"""Measure the serving engine's host-vs-device split at B=64 (VERDICT r2
item 6: host bookkeeping must be <10% of the decode tick).

Runs a 64-slot engine on a small-but-real model, fills every slot, decodes
a fixed number of ticks, and prints one JSON line with the split. On CPU
the "device" time is the jitted tick itself; on TPU it additionally
includes the tunnel RTT of the [B] token fetch.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import LLMEngine, Request

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=4, hidden_size=128,
                           num_attention_heads=8, num_key_value_heads=4,
                           intermediate_size=256, vocab_size=1024)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)

    slots = 64
    new_tokens = 48
    eng = LLMEngine(model, num_slots=slots, block_size=16,
                    max_prompt_len=64, max_seq_len=128)
    for _ in range(slots):
        n = int(rs.randint(8, 64))
        eng.add_request(Request(rs.randint(0, 1024, (n,)),
                                max_new_tokens=new_tokens))
    # admission tick (compiles prefill+tick); exclude from the measurement
    eng.step()
    eng.step()
    eng.stats = {"host_s": 0.0, "device_s": 0.0, "ticks": 0}
    t0 = time.perf_counter()
    while eng.has_work():
        eng.step()
    wall = time.perf_counter() - t0
    s = eng.stats
    host_frac = s["host_s"] / max(s["host_s"] + s["device_s"], 1e-9)
    print(json.dumps({
        "metric": "serving host fraction of decode tick (B=64)",
        "value": round(host_frac, 4), "unit": "fraction",
        "extra": {"ticks": s["ticks"],
                  "host_ms_per_tick": round(1e3 * s["host_s"] / s["ticks"], 3),
                  "device_ms_per_tick": round(1e3 * s["device_s"] / s["ticks"], 3),
                  "wall_s": round(wall, 2),
                  "device": str(jax.devices()[0]),
                  "target": "< 0.10"}}))


if __name__ == "__main__":
    main()
