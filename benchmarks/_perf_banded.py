"""On-chip experiment: WHY is the banded (sliding-window) flash grid slower
than full causal? (tpu_probe round-3 finding: 51.9ms vs 8.0ms at S=4096,
w=1024 — ~20x per-iteration cost.)

Variants timed (fwd only, S=4096, w=1024, bf16):
  full        — full causal grid, pl.when skips dead tiles (the fast case)
  band_arith  — banded grid, index map computes the band start inline
                (jnp.maximum / floordiv on grid indices) [current mainline]
  band_sp     — banded grid, band starts PRECOMPUTED into an int32 array
                and read from SMEM via PrefetchScalarGridSpec (splash-
                attention pattern)
  *_par       — same, with dimension_semantics=(parallel, parallel,
                arbitrary) declared

Timing notes (see .claude/skills/verify): block_until_ready is a NO-OP
over the axon tunnel; sync via float() host fetch, amortized over ITERS
calls. A no-op jit's time is printed alongside as the dispatch-overhead
floor — compare variants against it, it is NOT subtracted.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = "--interpret" in sys.argv  # CPU structural smoke (tiny shapes)
B, H, D = 1, 4, 128
S = 512 if INTERPRET else 4096
W = 256 if INTERPRET else 1024
BQ = BK = 128
ITERS = 2 if INTERPRET else 20
_NEG_INF = -1e30


def _mask(s, i, j, causal=True, window=W):
    q_idx = i * BQ + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_idx = j * BK + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = q_idx >= k_idx
    if window is not None:
        keep &= (q_idx - k_idx) < window
    return jnp.where(keep, s, _NEG_INF)


def _body(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc, *, i, j, jl, nsteps,
          window, live):
    @pl.when(jl == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (D ** -0.5)
        s = _mask(s, i, j, window=window)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_sc[:, 0] = l_sc[:, 0] * corr + jnp.sum(p, axis=1)
        m_sc[:, 0] = m_new
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv

    pl.when(live)(compute)

    @pl.when(jl == nsteps - 1)
    def _fin():
        o_ref[0] = (acc[:] / jnp.maximum(l_sc[:], 1e-30)).astype(o_ref.dtype)


def _scratch():
    return [pltpu.VMEM((BQ, D), jnp.float32),
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, 1), jnp.float32)]


def _band_start(i):
    return jnp.maximum(0, (i * BQ - W + 1) // BK)


NK = S // BK
NQ = S // BQ
N_BAND = min(NK, (W + BQ - 1) // BK + 1)


def make_full(par):
    def kernel(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc):
        i, j = pl.program_id(1), pl.program_id(2)
        live = j * BK <= i * BQ + BQ - 1
        _body(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc, i=i, j=j, jl=j,
              nsteps=NK, window=W, live=live)

    sem = (pltpu.CompilerParams(dimension_semantics=(
        pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)) if par else None)
    return pl.pallas_call(
        kernel, grid=(B * H, NQ, NK),
        in_specs=[pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
                  pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0))],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16),
        scratch_shapes=_scratch(),
        interpret=INTERPRET,
        **({"compiler_params": sem} if sem else {}),
    )


def make_band_arith(par):
    def kv_index(b, i, jl):
        return (b, jnp.minimum(_band_start(i) + jl, NK - 1), 0)

    def kernel(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc):
        i, jl = pl.program_id(1), pl.program_id(2)
        j = _band_start(i) + jl
        live = (j * BK <= i * BQ + BQ - 1) & (i * BQ - (j * BK + BK - 1) < W) \
            & (j < NK)
        _body(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc, i=i, j=j, jl=jl,
              nsteps=N_BAND, window=W, live=live)

    sem = (pltpu.CompilerParams(dimension_semantics=(
        pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)) if par else None)
    return pl.pallas_call(
        kernel, grid=(B * H, NQ, N_BAND),
        in_specs=[pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, BK, D), kv_index),
                  pl.BlockSpec((1, BK, D), kv_index)],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16),
        scratch_shapes=_scratch(),
        interpret=INTERPRET,
        **({"compiler_params": sem} if sem else {}),
    )


def make_band_sp(par):
    """Band starts precomputed host/XLA-side; index map reads SMEM."""
    def kv_index(b, i, jl, starts_ref):
        return (b, jnp.minimum(starts_ref[i] + jl, NK - 1), 0)

    def kernel(starts_ref, q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc):
        i, jl = pl.program_id(1), pl.program_id(2)
        j = starts_ref[i] + jl
        live = (j * BK <= i * BQ + BQ - 1) & (i * BQ - (j * BK + BK - 1) < W) \
            & (j < NK)
        _body(q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc, i=i, j=j, jl=jl,
              nsteps=N_BAND, window=W, live=live)

    sem = (pltpu.CompilerParams(dimension_semantics=(
        pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)) if par else None)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, NQ, N_BAND),
        in_specs=[pl.BlockSpec((1, BQ, D), lambda b, i, j, s: (b, i, 0)),
                  pl.BlockSpec((1, BK, D), kv_index),
                  pl.BlockSpec((1, BK, D), kv_index)],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j, s: (b, i, 0)),
        scratch_shapes=_scratch(),
    )
    inner = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16),
        interpret=INTERPRET,
        **({"compiler_params": sem} if sem else {}),
    )
    starts = jnp.asarray(
        np.maximum(0, (np.arange(NQ) * BQ - W + 1) // BK), jnp.int32)
    return lambda q, k, v: inner(starts, q, k, v)


def timeit(f, *args):
    out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))  # compile + sync
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))
    return (time.perf_counter() - t0) / ITERS


def main():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B * H, S, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B * H, S, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B * H, S, D), jnp.bfloat16)

    # dispatch overhead calibration
    nop = jax.jit(lambda x: x + 1)
    t_nop = timeit(nop, jnp.zeros((8, 128), jnp.bfloat16))
    print(f"dispatch/no-op: {t_nop*1e3:.3f} ms", flush=True)

    # every variant computes the SAME windowed-causal attention (the full
    # grid applies the window as an in-tile mask), so outputs must agree
    ref = None
    for name, make in [
        ("full", lambda: make_full(False)),
        ("full_par", lambda: make_full(True)),
        ("band_arith", lambda: make_band_arith(False)),
        ("band_arith_par", lambda: make_band_arith(True)),
        ("band_sp", lambda: make_band_sp(False)),
        ("band_sp_par", lambda: make_band_sp(True)),
    ]:
        try:
            f = jax.jit(make())
            t = timeit(f, q, k, v)
            out = np.asarray(f(q, k, v), np.float32)
            if ref is None:
                ref = out
            err = np.abs(out - ref).max()
            print(f"{name:16s} {t*1e3:8.3f} ms  (maxdiff vs first "
                  f"{err:.4f})", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name:16s} FAIL {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
