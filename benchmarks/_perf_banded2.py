"""On-chip diagnostic: WHERE does the mainline banded slowness live?

_perf_banded.py (round-3) proved the standalone banded grid variants are
fast (band_arith_par 0.77ms net at S=4096/w=1024) once dimension_semantics
is declared — and that declaration is now in the mainline kernel. Yet
tpu_probe still measures mainline banded at 57ms vs 2.9ms full (S=1024,
w=256, fwd-only). This script times the MAINLINE flash_attention at the
probe's exact shapes, fwd and fwd+bwd separately, against full causal,
plus the no-op dispatch floor — to localise the regression (fwd grid?
dq grid? dkv grid? dispatch?).
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas.flash_attention import flash_attention

ITERS = 20


def timeit(f, *args):
    out = f(*args)
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(x.astype(jnp.float32))), out)  # compile+sync
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = f(*args)
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    return (time.perf_counter() - t0) / ITERS


def run(tag, b, h, s, w):
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, 128), jnp.bfloat16)
    k = jnp.asarray(rs.randn(b, s, h, 128), jnp.bfloat16)
    v = jnp.asarray(rs.randn(b, s, h, 128), jnp.bfloat16)

    fwd_full = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    fwd_band = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                       window=w))

    def loss_full(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True
                                       ).astype(jnp.float32) ** 2)

    def loss_band(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=w
                                       ).astype(jnp.float32) ** 2)

    bwd_full = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))
    bwd_band = jax.jit(jax.grad(loss_band, argnums=(0, 1, 2)))

    t_ff = timeit(fwd_full, q, k, v)
    t_fb = timeit(fwd_band, q, k, v)
    t_bf = timeit(bwd_full, q, k, v)
    t_bb = timeit(bwd_band, q, k, v)
    print(f"{tag}: fwd full {t_ff*1e3:8.3f}  fwd band {t_fb*1e3:8.3f}  "
          f"bwd full {t_bf*1e3:8.3f}  bwd band {t_bb*1e3:8.3f}  (ms)",
          flush=True)


def main():
    nop = jax.jit(lambda x: x + 1)
    x0 = jnp.zeros((8, 128), jnp.bfloat16)
    t = timeit(nop, x0)
    print(f"dispatch/no-op: {t*1e3:.3f} ms", flush=True)
    run("probe-shape  B1 H2 S1024 w256 ", 1, 2, 1024, 256)
    run("probe-full   B1 H2 S4096 w1024", 1, 2, 4096, 1024)
    run("bigger       B4 H8 S4096 w1024", 4, 8, 4096, 1024)


if __name__ == "__main__":
    main()
